"""Live migration: state moves bitwise, ledgers stay monotonic, scale-out
rebalances without recompiling untouched tenants."""
import numpy as np
import pytest

from metrics_tpu.utils.exceptions import MetricsUserError
from metrics_tpu.cluster.migrate import PHASES

from tests.cluster.conftest import assert_matches_oracle, make_pipeline, post_stream

pytestmark = pytest.mark.cluster


class TestMigrate:
    def test_committed_move_preserves_state_bitwise(self, cluster_factory):
        coordinator, client = cluster_factory(n_replicas=2)
        tenants = [f"t{i}" for i in range(4)]
        log = post_stream(client, tenants, steps=3)
        tenant = tenants[0]
        src = coordinator.owner(tenant)
        dst = next(r for r in coordinator.replicas if r != src)
        phases = []
        record = coordinator.migrate(tenant, dst, on_phase=phases.append)
        assert record.outcome == "committed"
        assert record.phase == "done"
        assert phases == [p for p in PHASES if p != "done"]
        assert record.frames > 0 and record.bytes > 0
        assert record.downtime_s >= 0.0
        # state left the source entirely and landed on the destination
        assert tenant not in map(str, coordinator.replicas[src].tenant_ids())
        assert tenant in map(str, coordinator.replicas[dst].tenant_ids())
        assert coordinator.owner(tenant) == dst
        # every tenant (moved and unmoved) still reads bitwise-equal to the
        # pure-protocol replay of the admitted log
        assert_matches_oracle(client, log)

    def test_ledger_watermark_continues_monotonically(self, cluster_factory):
        coordinator, client = cluster_factory(n_replicas=2)
        log = post_stream(client, ["t0"], steps=4)
        src = coordinator.owner("t0")
        dst = next(r for r in coordinator.replicas if r != src)
        coordinator.migrate("t0", dst)
        doc = client.read("t0", max_staleness_steps=0, timeout_s=30.0)
        assert doc["last_applied_step"] == 4
        # new steps continue the same per-tenant step counter on the new home
        log += post_stream(client, ["t0"], steps=2, seed=1)
        doc = client.read("t0", max_staleness_steps=0, timeout_s=30.0)
        assert doc["last_applied_step"] == 6
        assert_matches_oracle(client, log)

    def test_posts_during_fence_ride_through(self, cluster_factory):
        coordinator, client = cluster_factory(n_replicas=2)
        log = post_stream(client, ["t0"], steps=2)
        src_id = coordinator.owner("t0")
        dst_id = next(r for r in coordinator.replicas if r != src_id)
        rng = np.random.default_rng(7)
        racing = []

        def on_phase(phase):
            # between fence and cutover the tenant's writes are rejected with
            # Retry-After; a backpressure-honoring caller lands them post-move
            if phase == "transfer":
                preds = rng.integers(0, 4, size=(8,)).astype(np.int32)
                target = rng.integers(0, 4, size=(8,)).astype(np.int32)
                doc = client.post("t0", preds, target)
                assert not doc["admitted"] and doc["reason"] == "tenant_fenced"
                racing.append((preds, target))

        record = coordinator.migrate("t0", dst_id, on_phase=on_phase)
        assert record.outcome == "committed" and racing
        for preds, target in racing:
            doc = client.post_with_retry("t0", preds, target)
            assert doc["admitted"], doc
            log.append(("t0", (preds, target), {}))
        assert_matches_oracle(client, log)

    def test_migrating_to_current_owner_is_refused(self, cluster_factory):
        coordinator, client = cluster_factory(n_replicas=2)
        post_stream(client, ["t0"], steps=1)
        with pytest.raises(MetricsUserError, match="nothing to migrate"):
            coordinator.migrate("t0", coordinator.owner("t0"))

    def test_migrating_unknown_tenant_aborts_cleanly(self, cluster_factory):
        coordinator, _ = cluster_factory(n_replicas=2)
        record = coordinator.migrate("ghost", "r1", src="r0")
        assert record.outcome == "aborted"
        assert "not resident" in record.error
        assert "ghost" not in map(str, coordinator.replicas["r1"].tenant_ids())

    def test_migrating_to_unknown_replica_is_refused(self, cluster_factory):
        coordinator, _ = cluster_factory(n_replicas=2)
        with pytest.raises(MetricsUserError, match="unknown destination"):
            coordinator.migrate("t0", "r9")


class TestRebalance:
    def test_scale_out_moves_load_onto_the_new_replica(self, cluster_factory):
        coordinator, client = cluster_factory(n_replicas=2)
        tenants = [f"t{i}" for i in range(8)]
        # skewed load: every third tenant is 4x hot
        log = []
        for i, tid in enumerate(tenants):
            log += post_stream(client, [tid], steps=1 + 3 * (i % 3), seed=i)
        for replica in coordinator.replicas.values():
            replica.pipeline.drain(30.0)

        new_replica = coordinator.add_replica("r2", make_pipeline("cl-r2"))
        assert new_replica.alive
        client.add_target("r2", new_replica)
        client.refresh_map()
        # membership change alone moves nothing: every live tenant was pinned
        assert all(coordinator.owner(t) in ("r0", "r1") for t in tenants)

        records = coordinator.rebalance(tolerance=0.10)
        assert records and all(r.outcome == "committed" for r in records)
        sizes = coordinator.status()["shard_sizes"]
        assert sizes["r2"] > 0
        assert sum(sizes.values()) == len(tenants)
        assert_matches_oracle(client, log)

    def test_untouched_tenants_see_zero_steady_state_recompiles(self, cluster_factory):
        coordinator, client = cluster_factory(n_replicas=2)
        tenants = [f"t{i}" for i in range(6)]

        def drained_round(targets, seed):
            # drain after every post so each dispatch is a width-1 bucket —
            # the compile counter is then deterministic, not timing-dependent
            out = []
            for step_seed, tid in enumerate(targets):
                out += post_stream(client, [tid], steps=1, seed=seed + step_seed)
                for replica in coordinator.replicas.values():
                    if replica.alive:
                        replica.pipeline.drain(30.0)
                client.read(tid, max_staleness_steps=0, timeout_s=30.0)
            return out

        log = drained_round(tenants, seed=0)

        new_replica = coordinator.add_replica("r2", make_pipeline("cl-r2"))
        client.add_target("r2", new_replica)
        client.refresh_map()
        records = coordinator.rebalance(tolerance=0.0, max_moves=2)
        moved = {r.tenant for r in records if r.outcome == "committed"}
        untouched = [t for t in tenants if t not in moved]
        assert untouched

        # one warm round after the scale-out (import/reset programs may trace
        # here, once), then steady state must be compile-free
        log += drained_round(untouched, seed=100)
        compiles_warm = {
            rid: replica.tenant_set.stats.compiles
            for rid, replica in coordinator.replicas.items()
        }
        log += drained_round(untouched, seed=200)
        for rid in ("r0", "r1"):
            replica = coordinator.replicas[rid]
            if not set(map(str, replica.tenant_ids())) & set(untouched):
                continue
            assert replica.tenant_set.stats.compiles == compiles_warm[rid], (
                f"{rid} recompiled while serving only warm, untouched tenants"
            )
        assert_matches_oracle(client, log)
