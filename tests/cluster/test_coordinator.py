"""Coordinator control plane: the status document, the read-only HTTP
endpoints, and the house observability contract (Prometheus series, tracer
event catalog, chaos sites)."""
import json
import urllib.request

import pytest

from metrics_tpu.observability import tracer as _otrace
from metrics_tpu.observability.instruments import REGISTRY
from metrics_tpu.observability.tracer import EVENT_CATALOG
from metrics_tpu.resilience.chaos import KNOWN_SITES

from tests.cluster.conftest import post_stream

pytestmark = pytest.mark.cluster


class TestStatusDocument:
    def test_status_names_every_replica_and_the_map(self, cluster_factory):
        coordinator, client = cluster_factory(n_replicas=2, name="doc")
        post_stream(client, ["t0", "t1", "t2"], steps=1)
        for replica in coordinator.replicas.values():
            replica.pipeline.drain(30.0)
        doc = coordinator.status()
        assert doc["name"] == "doc"
        assert doc["epoch"] == coordinator.shard_map.epoch
        assert doc["degraded"] is False
        assert sorted(doc["replicas"]) == ["r0", "r1"]
        assert all(r["alive"] for r in doc["replicas"].values())
        assert sum(doc["shard_sizes"].values()) == 3
        assert doc["migrations"] == {
            "total": 0, "committed": 0, "aborted": 0, "last": None,
        }

    def test_migration_outcomes_land_in_status(self, cluster_factory):
        coordinator, client = cluster_factory(n_replicas=2, name="mig")
        post_stream(client, ["t0"], steps=1)
        src = coordinator.owner("t0")
        dst = next(r for r in coordinator.replicas if r != src)
        coordinator.migrate("t0", dst)
        doc = coordinator.status()
        assert doc["migrations"]["committed"] == 1
        assert doc["migrations"]["last"]["tenant"] == "t0"
        assert doc["pins"] == 1  # the cutover pinned the tenant to its new home


class TestCoordinatorServer:
    def test_http_endpoints_serve_status_shardmap_healthz(self, cluster_factory):
        coordinator, client = cluster_factory(n_replicas=2, name="httpd")
        post_stream(client, ["t0"], steps=1)
        server = coordinator.serve_status(port=0)
        try:
            base = server.url
            with urllib.request.urlopen(f"{base}/status.json", timeout=10) as resp:
                status = json.loads(resp.read().decode())
            assert status["name"] == "httpd"
            with urllib.request.urlopen(f"{base}/shardmap", timeout=10) as resp:
                shardmap = json.loads(resp.read().decode())
            assert shardmap["epoch"] == coordinator.shard_map.epoch
            assert sorted(shardmap["replicas"]) == ["r0", "r1"]
            with urllib.request.urlopen(f"{base}/healthz", timeout=10) as resp:
                health = json.loads(resp.read().decode())
            assert health["status"] == "ok"
        finally:
            server.stop()


class TestObservabilityContract:
    def test_cluster_prometheus_series_are_exported(self, cluster_factory):
        coordinator, client = cluster_factory(n_replicas=2, name="prom")
        post_stream(client, ["t0", "t1"], steps=1)
        src = coordinator.owner("t0")
        dst = next(r for r in coordinator.replicas if r != src)
        coordinator.migrate("t0", dst)
        samples = {
            (s.name, s.labels.get("replica", ""), s.labels.get("outcome", "")): s.value
            for s in REGISTRY.samples()
            if s.labels.get("cluster") == "prom"
        }
        assert samples[("metrics_tpu_cluster_epoch", "", "")] == float(
            coordinator.shard_map.epoch
        )
        assert samples[("metrics_tpu_cluster_replicas", "", "")] == 2.0
        assert samples[("metrics_tpu_cluster_replicas_dead", "", "")] == 0.0
        shard_sizes = coordinator.status()["shard_sizes"]
        for rid in ("r0", "r1"):
            assert samples[
                ("metrics_tpu_cluster_shard_tenants", rid, "")
            ] == float(shard_sizes[rid])
        migrated = [
            value for (name, _, outcome), value in samples.items()
            if name == "metrics_tpu_cluster_migrations_total"
            and outcome == "committed"
        ]
        assert migrated == [1.0]
        assert any(
            s.name.startswith("metrics_tpu_cluster_fence_seconds")
            for s in REGISTRY.samples()
            if s.labels.get("cluster") == "prom"
        )

    def test_migration_emits_cataloged_trace_events(self, cluster_factory):
        coordinator, client = cluster_factory(n_replicas=2, name="trace")
        post_stream(client, ["t0"], steps=1)
        _otrace.enable()
        try:
            src = coordinator.owner("t0")
            dst = next(r for r in coordinator.replicas if r != src)
            coordinator.migrate("t0", dst)
        finally:
            _otrace.disable()
        tracer = _otrace.get_tracer()
        names = {e.name for e in tracer.events()}
        for phase in ("fence", "drain", "export", "transfer", "import", "cutover"):
            assert f"cluster/{phase}" in names, sorted(names)
        # every emitted cluster event is in the catalog — no drift
        catalog = {
            name for events in EVENT_CATALOG.values() for name in events
        }
        cluster_events = {n for n in names if n.startswith("cluster/")}
        assert cluster_events <= catalog

    def test_chaos_sites_are_registered(self):
        for phase in ("fence", "export", "transfer", "import", "cutover", "recover"):
            assert f"cluster/{phase}" in KNOWN_SITES
