"""Cluster suite hygiene and shared builders.

The chaos plan, tracer and instrument registry are process-global (same story
as the serve suite), and every test builds its own in-process cluster — the
factory fixture guarantees coordinators are stopped even when an assertion
fires mid-migration.
"""
import numpy as np
import pytest

from metrics_tpu import Accuracy, MeanSquaredError, MetricCollection
from metrics_tpu.observability import tracer as _otrace
from metrics_tpu.observability.instruments import REGISTRY
from metrics_tpu.resilience import chaos as _chaos
from metrics_tpu.serve import IngestPipeline, offline_replay
from metrics_tpu.serve import server as _iserver
from metrics_tpu.cluster import ClusterClient, ClusterCoordinator


@pytest.fixture(autouse=True)
def _pristine_cluster_globals():
    yield
    _chaos.uninstall()
    _iserver.shutdown(drain=False, timeout=5.0)
    _otrace.disable()
    tracer = _otrace.get_tracer()
    if tracer is not None:
        tracer.clear()
    REGISTRY.clear()


def build_collection():
    return MetricCollection({
        "acc": Accuracy(num_classes=4, average="micro"),
        "mse": MeanSquaredError(),
    })


def make_pipeline(name):
    return IngestPipeline(build_collection(), name=name)


@pytest.fixture
def cluster_factory(tmp_path):
    made = []

    def make(n_replicas=2, name="cl", checkpoint_root=None):
        coordinator = ClusterCoordinator(
            {
                f"r{i}": make_pipeline(f"{name}-r{i}")
                for i in range(n_replicas)
            },
            name=name,
            checkpoint_root=str(tmp_path / "ckpt") if checkpoint_root else None,
        ).start()
        made.append(coordinator)
        client = ClusterClient(dict(coordinator.replicas), coordinator)
        return coordinator, client

    yield make
    for coordinator in made:
        coordinator.stop(drain=False, timeout=5.0)


def post_stream(client, tenants, steps=3, seed=0):
    """Post a deterministic stream; returns the admission-ordered oracle log."""
    rng = np.random.default_rng(seed)
    log = []
    for step in range(steps):
        for tid in tenants:
            preds = rng.integers(0, 4, size=(8,)).astype(np.int32)
            target = rng.integers(0, 4, size=(8,)).astype(np.int32)
            doc = client.post_with_retry(tid, preds, target)
            assert doc.get("admitted"), doc
            log.append((tid, (preds, target), {}))
    return log


def assert_matches_oracle(client, log):
    """Every tenant's served read must equal the pure-protocol replay bitwise."""
    oracle = offline_replay(build_collection, log)
    for tid in sorted({t for t, _, _ in log}):
        doc = client.read(tid, max_staleness_steps=0, timeout_s=30.0)
        assert doc.get("values") is not None, doc
        for name, expected in oracle[tid].items():
            got = np.asarray(doc["values"][name], dtype=expected.dtype)
            np.testing.assert_array_equal(got, expected, err_msg=f"{tid}/{name}")
