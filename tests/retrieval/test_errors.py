"""Argument-error matrix for retrieval metrics.

Reference parity: tests/retrieval/helpers.py:429 (`_errors_test_class_metric` /
`_errors_test_functional_metric` parametrizations) — every retrieval class and
functional must reject malformed indexes/preds/target and bad constructor
arguments with the documented exception types.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import (
    RetrievalFallOut,
    RetrievalHitRate,
    RetrievalMAP,
    RetrievalMRR,
    RetrievalNormalizedDCG,
    RetrievalPrecision,
    RetrievalRecall,
    RetrievalRPrecision,
    ops,
)

ALL_CLASSES = [
    RetrievalMAP,
    RetrievalMRR,
    RetrievalPrecision,
    RetrievalRecall,
    RetrievalHitRate,
    RetrievalFallOut,
    RetrievalNormalizedDCG,
    RetrievalRPrecision,
]
K_CLASSES = [RetrievalPrecision, RetrievalRecall, RetrievalHitRate, RetrievalFallOut, RetrievalNormalizedDCG]
ALL_FUNCTIONALS = [
    ops.retrieval_average_precision,
    ops.retrieval_reciprocal_rank,
    ops.retrieval_precision,
    ops.retrieval_recall,
    ops.retrieval_hit_rate,
    ops.retrieval_fall_out,
    ops.retrieval_r_precision,
]

_PREDS = jnp.asarray([0.2, 0.7, 0.4])
_TARGET = jnp.asarray([0, 1, 0])
_INDEXES = jnp.asarray([0, 0, 0])


@pytest.mark.parametrize("metric_cls", ALL_CLASSES, ids=lambda c: c.__name__)
class TestClassArgErrors:
    def test_invalid_empty_target_action(self, metric_cls):
        with pytest.raises(ValueError, match="empty_target_action"):
            metric_cls(empty_target_action="casual_videos")

    def test_invalid_ignore_index(self, metric_cls):
        with pytest.raises(ValueError, match="ignore_index"):
            metric_cls(ignore_index=-1.5)

    def test_indexes_none(self, metric_cls):
        with pytest.raises(ValueError, match="`indexes` cannot be None"):
            metric_cls().update(_PREDS, _TARGET, indexes=None)

    def test_indexes_wrong_dtype(self, metric_cls):
        with pytest.raises(ValueError, match="integer"):
            metric_cls().update(_PREDS, _TARGET, indexes=jnp.asarray([0.0, 0.0, 0.0]))

    def test_mismatched_shapes(self, metric_cls):
        with pytest.raises(ValueError, match="shape"):
            metric_cls().update(_PREDS, _TARGET[:2], indexes=_INDEXES)
        with pytest.raises(ValueError, match="shape"):
            metric_cls().update(_PREDS, _TARGET, indexes=_INDEXES[:2])

    def test_empty_inputs(self, metric_cls):
        with pytest.raises(ValueError, match="at least one element"):
            metric_cls().update(jnp.zeros((0,)), jnp.zeros((0,), jnp.int32), indexes=jnp.zeros((0,), jnp.int32))

    def test_preds_not_float(self, metric_cls):
        with pytest.raises(ValueError, match="float"):
            metric_cls().update(jnp.asarray([1, 0, 2]), _TARGET, indexes=_INDEXES)

    def test_non_binary_target(self, metric_cls):
        if metric_cls is RetrievalNormalizedDCG:
            pytest.skip("NDCG allows graded relevance")
        with pytest.raises(ValueError, match="binary"):
            metric_cls().update(_PREDS, jnp.asarray([0, 3, 1]), indexes=_INDEXES)


@pytest.mark.parametrize("metric_cls", K_CLASSES, ids=lambda c: c.__name__)
def test_invalid_k(metric_cls):
    with pytest.raises(ValueError, match="`k`"):
        metric_cls(k=-2)
    with pytest.raises(ValueError, match="`k`"):
        metric_cls(k=1.5)


@pytest.mark.parametrize("fn", ALL_FUNCTIONALS, ids=lambda f: f.__name__)
class TestFunctionalArgErrors:
    def test_mismatched_shapes(self, fn):
        with pytest.raises(ValueError, match="shape"):
            fn(_PREDS, _TARGET[:2])

    def test_empty_inputs(self, fn):
        with pytest.raises(ValueError, match="at least one element"):
            fn(jnp.zeros((0,)), jnp.zeros((0,), jnp.int32))

    def test_non_binary_target(self, fn):
        with pytest.raises(ValueError, match="binary"):
            fn(_PREDS, jnp.asarray([0, 3, 1]))
