"""Retrieval class-metric value grid: every metric x every option combination.

Reference analog: each reference retrieval test file sweeps
empty_target_action x ignore_index x k through RetrievalMetricTester
(tests/retrieval/helpers.py:150-420). Here one parametrized grid covers all
ten classes against an independent numpy per-query oracle that reimplements
the option semantics from the documented contract: group by query index, drop
``ignore_index`` documents, then handle all-negative queries per
``empty_target_action`` (skip / score 0 / score 1 / raise).
"""
import numpy as np
import pytest

import jax.numpy as jnp

import metrics_tpu as M

N_DOCS = 96
N_QUERIES = 7


def _fixture(with_ignore: bool, with_empty: bool):
    """(indexes, preds, target) with controllable pathologies. A fresh seeded
    rng per call keeps every parametrized cell deterministic in isolation
    (running one cell alone draws the same data as the full suite)."""
    _rng = np.random.default_rng(17)
    indexes = np.sort(_rng.integers(0, N_QUERIES, N_DOCS))
    preds = _rng.random(N_DOCS).astype(np.float32)
    target = _rng.integers(0, 2, N_DOCS)
    if with_empty:  # make queries 0 and 3 all-negative
        target[np.isin(indexes, [0, 3])] = 0
    else:  # every query has at least one positive
        for q in range(N_QUERIES):
            sel = np.flatnonzero(indexes == q)
            if sel.size and target[sel].sum() == 0:
                target[sel[0]] = 1
    if with_ignore:  # sprinkle ignored docs
        target[_rng.choice(N_DOCS, 10, replace=False)] = -1
    return indexes, preds, target


# ---------------------------------------------------------------- oracles --
def _ap(p, t):
    order = np.argsort(-p, kind="stable")
    t = t[order]
    hits = np.cumsum(t)
    prec = hits / np.arange(1, len(t) + 1)
    return float((prec * t).sum() / max(t.sum(), 1))


def _mrr(p, t):
    order = np.argsort(-p, kind="stable")
    pos = np.flatnonzero(t[order])
    return float(1.0 / (pos[0] + 1)) if pos.size else 0.0


def _precision_at(k):
    def fn(p, t):
        order = np.argsort(-p, kind="stable")[:k]
        return float(t[order].sum() / min(k, len(t)))
    return fn


def _recall_at(k):
    def fn(p, t):
        order = np.argsort(-p, kind="stable")[:k]
        return float(t[order].sum() / max(t.sum(), 1))
    return fn


def _hit_rate_at(k):
    def fn(p, t):
        order = np.argsort(-p, kind="stable")[:k]
        return float(t[order].any())
    return fn


def _fall_out_at(k):
    def fn(p, t):
        order = np.argsort(-p, kind="stable")[:k]
        neg = (1 - t)
        return float(neg[order].sum() / max(neg.sum(), 1))
    return fn


def _r_precision(p, t):
    r = int(t.sum())
    order = np.argsort(-p, kind="stable")[:r]
    return float(t[order].sum() / max(r, 1))


def _ndcg_at(k):
    def fn(p, t):
        kk = min(k or len(t), len(t))
        order = np.argsort(-p, kind="stable")[:kk]
        gains = (2.0 ** t[order] - 1) / np.log2(np.arange(2, kk + 2))
        ideal_t = np.sort(t)[::-1][:kk]
        ideal = (2.0 ** ideal_t - 1) / np.log2(np.arange(2, kk + 2))
        return float(gains.sum() / max(ideal.sum(), 1e-12))
    return fn


def _oracle(metric_name, per_query_fn, indexes, preds, target, empty_action, ignore_index):
    # fall-out's degenerate queries are all-POSITIVE ones (no negatives to
    # rank; reference fall_out.py:24) — every other metric degenerates on
    # all-negative queries
    def degenerate(t):
        if metric_name == "RetrievalFallOut":
            return (1 - np.clip(t, 0, 1)).sum() == 0
        return t.sum() == 0

    vals = []
    for q in np.unique(indexes):
        sel = indexes == q
        p, t = preds[sel], target[sel]
        if ignore_index is not None:
            keep = t != ignore_index
            p, t = p[keep], t[keep]
        if t.size == 0:
            continue
        if degenerate(t):
            if empty_action == "skip":
                continue
            if empty_action == "neg":
                vals.append(0.0)
                continue
            if empty_action == "pos":
                vals.append(1.0)
                continue
        vals.append(per_query_fn(p, np.clip(t, 0, 1)))
    return float(np.mean(vals)) if vals else 0.0


_K = 3
_GRID = [
    ("RetrievalMAP", {}, _ap),
    ("RetrievalMRR", {}, _mrr),
    ("RetrievalPrecision", {"k": _K}, _precision_at(_K)),
    ("RetrievalRecall", {"k": _K}, _recall_at(_K)),
    ("RetrievalHitRate", {"k": _K}, _hit_rate_at(_K)),
    ("RetrievalFallOut", {"k": _K}, _fall_out_at(_K)),
    ("RetrievalRPrecision", {}, _r_precision),
    ("RetrievalNormalizedDCG", {"k": _K}, _ndcg_at(_K)),
]


@pytest.mark.parametrize("empty_action", ["skip", "neg", "pos"])
@pytest.mark.parametrize("with_ignore", [False, True], ids=["plain", "ignore-index"])
@pytest.mark.parametrize("name,kwargs,per_query", _GRID, ids=[g[0] for g in _GRID])
def test_option_grid_vs_numpy_oracle(name, kwargs, per_query, empty_action, with_ignore):
    indexes, preds, target = _fixture(with_ignore, with_empty=True)
    if name == "RetrievalFallOut":
        # give fall-out its own degenerate case: make query 5 all-POSITIVE
        target = target.copy()
        target[indexes == 5] = 1

    m = getattr(M, name)(
        empty_target_action=empty_action,
        ignore_index=-1 if with_ignore else None,
        **kwargs,
    )
    m.update(jnp.asarray(preds), jnp.asarray(target), indexes=jnp.asarray(indexes))
    got = float(m.compute())

    want = _oracle(name, per_query, indexes, preds, target, empty_action,
                   -1 if with_ignore else None)
    np.testing.assert_allclose(got, want, atol=1e-5, err_msg=f"{name} {empty_action}")


@pytest.mark.parametrize("name,kwargs,per_query", _GRID, ids=[g[0] for g in _GRID])
def test_option_grid_error_action_raises(name, kwargs, per_query):
    if name == "RetrievalFallOut":
        pytest.skip("fall-out raises on all-positive queries instead")
    indexes, preds, target = _fixture(False, with_empty=True)
    m = getattr(M, name)(empty_target_action="error", **kwargs)
    m.update(jnp.asarray(preds), jnp.asarray(target), indexes=jnp.asarray(indexes))
    with pytest.raises(Exception):
        m.compute()


@pytest.mark.parametrize("empty_action", ["skip", "neg", "pos"])
@pytest.mark.parametrize("with_ignore", [False, True], ids=["plain", "ignore-index"])
@pytest.mark.parametrize("name,kwargs,per_query", _GRID, ids=[g[0] for g in _GRID])
def test_option_grid_compiled_path(name, kwargs, per_query, empty_action, with_ignore):
    """The static-shape compiled evaluation obeys the same option grid."""
    indexes, preds, target = _fixture(with_ignore, with_empty=True)
    if name == "RetrievalFallOut":
        target = target.copy()
        target[indexes == 5] = 1

    m = getattr(M, name)(
        empty_target_action=empty_action,
        ignore_index=-1 if with_ignore else None,
        max_queries=N_QUERIES + 1,
        max_docs_per_query=N_DOCS,
        **kwargs,
    )
    m.update(jnp.asarray(preds), jnp.asarray(target), indexes=jnp.asarray(indexes))
    got = float(m.compute())
    want = _oracle(name, per_query, indexes, preds, target, empty_action,
                   -1 if with_ignore else None)
    np.testing.assert_allclose(got, want, atol=1e-5, err_msg=f"{name} {empty_action} compiled")


@pytest.mark.parametrize("k", [1, 2, 5, None], ids=lambda k: f"k={k}")
def test_k_sweep_vs_oracle(k):
    indexes, preds, target = _fixture(False, with_empty=False)
    kwargs = {} if k is None else {"k": k}
    for name, per_query in [
        ("RetrievalPrecision", _precision_at(k or N_DOCS)),
        ("RetrievalRecall", _recall_at(k or N_DOCS)),
        ("RetrievalNormalizedDCG", _ndcg_at(k)),
    ]:
        m = getattr(M, name)(**kwargs)
        m.update(jnp.asarray(preds), jnp.asarray(target), indexes=jnp.asarray(indexes))
        want = _oracle(name, per_query, indexes, preds, target, "neg", None)
        np.testing.assert_allclose(float(m.compute()), want, atol=1e-5, err_msg=f"{name} k={k}")
