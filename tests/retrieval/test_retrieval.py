"""Retrieval metric parity tests.

Reference parity: tests/retrieval/* (compacted; sklearn + hand-numpy oracles).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import average_precision_score as sk_ap
from sklearn.metrics import ndcg_score as sk_ndcg

from metrics_tpu.ops.retrieval import (
    retrieval_average_precision,
    retrieval_fall_out,
    retrieval_hit_rate,
    retrieval_normalized_dcg,
    retrieval_precision,
    retrieval_precision_recall_curve,
    retrieval_r_precision,
    retrieval_reciprocal_rank,
    retrieval_recall,
)
from metrics_tpu.retrieval import (
    RetrievalFallOut,
    RetrievalHitRate,
    RetrievalMAP,
    RetrievalMRR,
    RetrievalNormalizedDCG,
    RetrievalPrecision,
    RetrievalPrecisionRecallCurve,
    RetrievalRecall,
    RetrievalRecallAtFixedPrecision,
    RetrievalRPrecision,
)

_rng = np.random.default_rng(99)
N_QUERIES, DOCS = 6, 10
_preds = _rng.random((N_QUERIES, DOCS)).astype(np.float32)
_target = _rng.integers(0, 2, (N_QUERIES, DOCS))
_target[:, 0] = 1  # every query has at least one positive and one negative
_target[:, 1] = 0
_indexes = np.repeat(np.arange(N_QUERIES), DOCS)


def test_ap_single_query():
    res = retrieval_average_precision(jnp.asarray(_preds[0]), jnp.asarray(_target[0]))
    np.testing.assert_allclose(np.asarray(res), sk_ap(_target[0], _preds[0]), atol=1e-6)


def test_mrr_single_query():
    order = np.argsort(-_preds[0], kind="stable")
    first_pos = np.nonzero(_target[0][order])[0][0]
    res = retrieval_reciprocal_rank(jnp.asarray(_preds[0]), jnp.asarray(_target[0]))
    np.testing.assert_allclose(np.asarray(res), 1.0 / (first_pos + 1), atol=1e-6)


@pytest.mark.parametrize("k", [1, 3, None])
def test_precision_recall_at_k(k):
    order = np.argsort(-_preds[0], kind="stable")
    kk = k or DOCS
    rel_at_k = _target[0][order][:kk].sum()
    res_p = retrieval_precision(jnp.asarray(_preds[0]), jnp.asarray(_target[0]), k=k)
    res_r = retrieval_recall(jnp.asarray(_preds[0]), jnp.asarray(_target[0]), k=k)
    np.testing.assert_allclose(np.asarray(res_p), rel_at_k / kk, atol=1e-6)
    np.testing.assert_allclose(np.asarray(res_r), rel_at_k / _target[0].sum(), atol=1e-6)


def test_hit_rate_fall_out_rprecision():
    order = np.argsort(-_preds[0], kind="stable")
    hr = retrieval_hit_rate(jnp.asarray(_preds[0]), jnp.asarray(_target[0]), k=2)
    assert float(hr) == float(_target[0][order][:2].sum() > 0)
    neg = 1 - _target[0]
    fo = retrieval_fall_out(jnp.asarray(_preds[0]), jnp.asarray(_target[0]), k=3)
    np.testing.assert_allclose(np.asarray(fo), neg[order][:3].sum() / neg.sum(), atol=1e-6)
    nrel = _target[0].sum()
    rp = retrieval_r_precision(jnp.asarray(_preds[0]), jnp.asarray(_target[0]))
    np.testing.assert_allclose(np.asarray(rp), _target[0][order][:nrel].sum() / nrel, atol=1e-6)


def test_ndcg_vs_sklearn():
    res = retrieval_normalized_dcg(jnp.asarray(_preds[0]), jnp.asarray(_target[0]))
    sk = sk_ndcg(_target[0][None], _preds[0][None])
    np.testing.assert_allclose(np.asarray(res), sk, atol=1e-6)


def test_map_class_grouped():
    m = RetrievalMAP()
    m.update(jnp.asarray(_preds.reshape(-1)), jnp.asarray(_target.reshape(-1)), indexes=jnp.asarray(_indexes))
    expected = np.mean([sk_ap(_target[i], _preds[i]) for i in range(N_QUERIES)])
    np.testing.assert_allclose(np.asarray(m.compute()), expected, atol=1e-6)


@pytest.mark.parametrize(
    "cls,kwargs",
    [
        (RetrievalMRR, {}),
        (RetrievalPrecision, {"k": 3}),
        (RetrievalRecall, {"k": 3}),
        (RetrievalHitRate, {"k": 3}),
        (RetrievalNormalizedDCG, {}),
        (RetrievalRPrecision, {}),
        (RetrievalFallOut, {"k": 3}),
    ],
)
def test_modules_run_and_accumulate(cls, kwargs):
    m = cls(**kwargs)
    for i in range(N_QUERIES):
        m.update(jnp.asarray(_preds[i]), jnp.asarray(_target[i]), indexes=jnp.full(DOCS, i, dtype=jnp.int32))
    val = float(m.compute())
    assert 0.0 <= val <= 1.0


@pytest.mark.parametrize("action,expected", [("neg", 0.5), ("pos", 1.0), ("skip", 1.0)])
def test_empty_target_action(action, expected):
    m = RetrievalMAP(empty_target_action=action)
    preds = jnp.asarray([0.9, 0.1, 0.8, 0.2])
    target = jnp.asarray([1, 0, 0, 0])
    indexes = jnp.asarray([0, 0, 1, 1])  # query 1 has no positives
    m.update(preds, target, indexes=indexes)
    np.testing.assert_allclose(float(m.compute()), expected, atol=1e-6)


def test_empty_target_error():
    m = RetrievalMAP(empty_target_action="error")
    m.update(jnp.asarray([0.9, 0.1]), jnp.asarray([0, 0]), indexes=jnp.asarray([0, 0]))
    with pytest.raises(ValueError, match="no positive target"):
        m.compute()


def test_ignore_index_filters():
    m = RetrievalMAP(ignore_index=-1)
    m.update(jnp.asarray([0.9, 0.1, 0.5]), jnp.asarray([1, -1, 0]), indexes=jnp.asarray([0, 0, 0]))
    np.testing.assert_allclose(float(m.compute()), sk_ap([1, 0], [0.9, 0.5]), atol=1e-6)


def test_pr_curve_reference_docstring():
    """Values from reference retrieval/precision_recall_curve.py:101-110."""
    indexes = jnp.asarray([0, 0, 0, 0, 1, 1, 1])
    preds = jnp.asarray([0.4, 0.01, 0.5, 0.6, 0.2, 0.3, 0.5])
    target = jnp.asarray([True, False, False, True, True, False, True])
    r = RetrievalPrecisionRecallCurve(max_k=4)
    precisions, recalls, top_k = r(preds, target, indexes=indexes)
    np.testing.assert_allclose(np.asarray(precisions), [1.0, 0.5, 0.6667, 0.5], atol=1e-4)
    np.testing.assert_allclose(np.asarray(recalls), [0.5, 0.5, 1.0, 1.0], atol=1e-4)
    np.testing.assert_array_equal(np.asarray(top_k), [1, 2, 3, 4])


def test_recall_at_fixed_precision_reference_docstring():
    indexes = jnp.asarray([0, 0, 0, 0, 1, 1, 1])
    preds = jnp.asarray([0.4, 0.01, 0.5, 0.6, 0.2, 0.3, 0.5])
    target = jnp.asarray([True, False, False, True, True, False, True])
    r = RetrievalRecallAtFixedPrecision(min_precision=0.8)
    max_recall, best_k = r(preds, target, indexes=indexes)
    np.testing.assert_allclose(float(max_recall), 0.5, atol=1e-6)
    assert int(best_k) == 1


def test_map_ddp_sync():
    """Distributed: per-device queries, cat-gathered state, global MAP."""
    from jax.sharding import Mesh, PartitionSpec as P

    devices = jax.devices()
    if len(devices) < 2:
        pytest.skip("needs >= 2 devices")
    world = 2
    mesh = Mesh(np.asarray(devices[:world]), ("data",))
    m = RetrievalMAP()

    idx = jnp.asarray(np.stack([_indexes[: 3 * DOCS], _indexes[3 * DOCS:]]))
    pr = jnp.asarray(np.stack([_preds[:3].reshape(-1), _preds[3:].reshape(-1)]))
    tg = jnp.asarray(np.stack([_target[:3].reshape(-1), _target[3:].reshape(-1)]))

    def body(i, p, t):
        state = m.update_state(m.init_state(), p[0], t[0], i[0])
        state = m.sync_states(state, "data")
        return jax.tree.map(lambda x: jnp.expand_dims(x, 0), state)

    out = jax.jit(
        jax.shard_map(body, mesh=mesh, in_specs=(P("data"), P("data"), P("data")), out_specs=P("data"), check_vma=False)
    )(idx, pr, tg)
    synced = jax.tree.map(lambda x: x[0], out)
    result = m.compute_state(synced)
    expected = np.mean([sk_ap(_target[i], _preds[i]) for i in range(N_QUERIES)])
    np.testing.assert_allclose(np.asarray(result), expected, atol=1e-6)


def test_pr_curve_adaptive_k_unequal_groups():
    """Regression: adaptive_k with different docs-per-query pads curves to
    max_k with saturated values (reference functional :83-86) instead of
    producing unstackable ragged curves."""
    r = RetrievalPrecisionRecallCurve(adaptive_k=True)
    r.update(
        jnp.asarray([0.4, 0.01, 0.5, 0.6, 0.2, 0.3, 0.5]),
        jnp.asarray([1, 0, 0, 1, 1, 0, 1]),
        indexes=jnp.asarray([0, 0, 0, 0, 1, 1, 1]),
    )
    p, rec, k = r.compute()
    assert p.shape == (4,) and rec.shape == (4,)
    np.testing.assert_allclose(np.asarray(p), [1.0, 0.5, 2 / 3, 0.583333], atol=1e-5)
    np.testing.assert_allclose(np.asarray(rec), [0.5, 0.5, 1.0, 1.0], atol=1e-5)


def test_recall_at_fixed_precision_tie_breaks_to_larger_k():
    """Regression: equal recalls at several k must report the LARGEST k
    (reference max over (r, k) tuples, precision_recall_curve.py:43)."""
    from metrics_tpu.retrieval.precision_recall_curve import _retrieval_recall_at_fixed_precision

    precision = jnp.asarray([1.0, 1.0])
    recall = jnp.asarray([1.0, 1.0])
    top_k = jnp.asarray([1, 2])
    max_recall, best_k = _retrieval_recall_at_fixed_precision(precision, recall, top_k, 0.5)
    assert float(max_recall) == 1.0
    assert int(best_k) == 2
