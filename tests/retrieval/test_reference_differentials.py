"""Retrieval option grid pinned directly against the reference classes.

The repo's option grid asserts against a self-written numpy per-query
oracle; this module removes the self-oracle risk by running the reference
RetrievalMetric classes live on the same (indexes, preds, target) streams
across empty_target_action × ignore_index (reference retrieval/base.py:27,
fall_out.py:24). Uses the shared conftest import helper.
"""
import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu as M
from tests.conftest import import_reference_torchmetrics
from tests.retrieval.test_option_grid import _K, _fixture

_PAIRS = [
    ("RetrievalMAP", "RetrievalMAP", {}),
    ("RetrievalMRR", "RetrievalMRR", {}),
    ("RetrievalPrecision", "RetrievalPrecision", {"k": _K}),
    ("RetrievalRecall", "RetrievalRecall", {"k": _K}),
    ("RetrievalHitRate", "RetrievalHitRate", {"k": _K}),
    ("RetrievalFallOut", "RetrievalFallOut", {"k": _K}),
    ("RetrievalRPrecision", "RetrievalRPrecision", {}),
    ("RetrievalNormalizedDCG", "RetrievalNormalizedDCG", {"k": _K}),
]


@pytest.mark.parametrize("empty_action", ["skip", "neg", "pos"])
@pytest.mark.parametrize("with_ignore", [False, True], ids=["plain", "ignore-index"])
@pytest.mark.parametrize("ours_name,ref_name,kwargs", _PAIRS, ids=[p[0] for p in _PAIRS])
def test_option_grid_vs_reference(ours_name, ref_name, kwargs, empty_action, with_ignore):
    import_reference_torchmetrics()
    import torch
    import torchmetrics

    indexes, preds, target = _fixture(with_ignore, with_empty=True)
    if ours_name == "RetrievalFallOut":
        target = target.copy()
        target[indexes == 5] = 1  # fall-out degenerates on all-positive queries

    ignore_index = -1 if with_ignore else None
    ours = getattr(M, ours_name)(empty_target_action=empty_action, ignore_index=ignore_index, **kwargs)
    ours.update(jnp.asarray(preds), jnp.asarray(target), indexes=jnp.asarray(indexes))

    ref = getattr(torchmetrics, ref_name)(
        empty_target_action=empty_action, ignore_index=ignore_index, **kwargs
    )
    ref.update(torch.tensor(preds), torch.tensor(target), indexes=torch.tensor(indexes))

    np.testing.assert_allclose(float(ours.compute()), float(ref.compute()), atol=1e-5)


@pytest.mark.parametrize("adaptive_k", [False, True], ids=["fixed-k", "adaptive-k"])
@pytest.mark.parametrize("max_k", [None, 4])
def test_precision_recall_curve_vs_reference(max_k, adaptive_k):
    from tests.conftest import reference_modular

    torch, torchmetrics = reference_modular()
    indexes, preds, target = _fixture(with_ignore=False, with_empty=False)
    ours = M.RetrievalPrecisionRecallCurve(max_k=max_k, adaptive_k=adaptive_k)
    ours.update(jnp.asarray(preds), jnp.asarray(target), indexes=jnp.asarray(indexes))
    ref = torchmetrics.RetrievalPrecisionRecallCurve(max_k=max_k, adaptive_k=adaptive_k)
    ref.update(torch.tensor(preds), torch.tensor(target), indexes=torch.tensor(indexes))
    o_prec, o_rec, o_k = ours.compute()
    r_prec, r_rec, r_k = ref.compute()
    np.testing.assert_allclose(np.asarray(o_k), np.asarray(r_k))
    np.testing.assert_allclose(np.asarray(o_prec), np.asarray(r_prec), atol=1e-6)
    np.testing.assert_allclose(np.asarray(o_rec), np.asarray(r_rec), atol=1e-6)


@pytest.mark.parametrize("min_precision", [0.0, 0.4, 0.8])
def test_recall_at_fixed_precision_vs_reference(min_precision):
    from tests.conftest import reference_modular

    torch, torchmetrics = reference_modular()
    indexes, preds, target = _fixture(with_ignore=False, with_empty=False)
    ours = M.RetrievalRecallAtFixedPrecision(min_precision=min_precision, max_k=6)
    ours.update(jnp.asarray(preds), jnp.asarray(target), indexes=jnp.asarray(indexes))
    ref = torchmetrics.RetrievalRecallAtFixedPrecision(min_precision=min_precision, max_k=6)
    ref.update(torch.tensor(preds), torch.tensor(target), indexes=torch.tensor(indexes))
    o_rec, o_k = ours.compute()
    r_rec, r_k = ref.compute()
    np.testing.assert_allclose(float(o_rec), float(r_rec), atol=1e-6)
    assert int(o_k) == int(r_k)
