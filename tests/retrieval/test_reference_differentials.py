"""Retrieval option grid pinned directly against the reference classes.

The repo's option grid asserts against a self-written numpy per-query
oracle; this module removes the self-oracle risk by running the reference
RetrievalMetric classes live on the same (indexes, preds, target) streams
across empty_target_action × ignore_index (reference retrieval/base.py:27,
fall_out.py:24). Uses the shared conftest import helper.
"""
import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu as M
from tests.conftest import import_reference_torchmetrics
from tests.retrieval.test_option_grid import _K, _fixture

_PAIRS = [
    ("RetrievalMAP", "RetrievalMAP", {}),
    ("RetrievalMRR", "RetrievalMRR", {}),
    ("RetrievalPrecision", "RetrievalPrecision", {"k": _K}),
    ("RetrievalRecall", "RetrievalRecall", {"k": _K}),
    ("RetrievalHitRate", "RetrievalHitRate", {"k": _K}),
    ("RetrievalFallOut", "RetrievalFallOut", {"k": _K}),
    ("RetrievalRPrecision", "RetrievalRPrecision", {}),
    ("RetrievalNormalizedDCG", "RetrievalNormalizedDCG", {"k": _K}),
]


@pytest.mark.parametrize("empty_action", ["skip", "neg", "pos"])
@pytest.mark.parametrize("with_ignore", [False, True], ids=["plain", "ignore-index"])
@pytest.mark.parametrize("ours_name,ref_name,kwargs", _PAIRS, ids=[p[0] for p in _PAIRS])
def test_option_grid_vs_reference(ours_name, ref_name, kwargs, empty_action, with_ignore):
    import_reference_torchmetrics()
    import torch
    import torchmetrics

    indexes, preds, target = _fixture(with_ignore, with_empty=True)
    if ours_name == "RetrievalFallOut":
        target = target.copy()
        target[indexes == 5] = 1  # fall-out degenerates on all-positive queries

    ignore_index = -1 if with_ignore else None
    ours = getattr(M, ours_name)(empty_target_action=empty_action, ignore_index=ignore_index, **kwargs)
    ours.update(jnp.asarray(preds), jnp.asarray(target), indexes=jnp.asarray(indexes))

    ref = getattr(torchmetrics, ref_name)(
        empty_target_action=empty_action, ignore_index=ignore_index, **kwargs
    )
    ref.update(torch.tensor(preds), torch.tensor(target), indexes=torch.tensor(indexes))

    np.testing.assert_allclose(float(ours.compute()), float(ref.compute()), atol=1e-5)
