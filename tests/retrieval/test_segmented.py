"""Compiled (static-shape) retrieval evaluation vs the eager per-query loop.

VERDICT item 6 'done' criteria: RetrievalMAP.compute_state jittable + parity
vs the eager path on randomized fixtures across all retrieval metrics.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import (
    RetrievalFallOut,
    RetrievalHitRate,
    RetrievalMAP,
    RetrievalMRR,
    RetrievalNormalizedDCG,
    RetrievalPrecision,
    RetrievalRecall,
    RetrievalRPrecision,
)
from metrics_tpu.utils.exceptions import MetricsUserError

_rng = np.random.default_rng(13)

METRICS = [
    (RetrievalMAP, {}),
    (RetrievalMRR, {}),
    (RetrievalPrecision, {}),
    (RetrievalPrecision, {"k": 3}),
    (RetrievalPrecision, {"k": 9, "adaptive_k": True}),
    (RetrievalRecall, {}),
    (RetrievalRecall, {"k": 3}),
    (RetrievalHitRate, {"k": 2}),
    (RetrievalFallOut, {"k": 3}),
    (RetrievalNormalizedDCG, {}),
    (RetrievalNormalizedDCG, {"k": 4}),
    (RetrievalRPrecision, {}),
]


def _fixture(n=160, n_queries=12):
    """Ragged queries (1..~26 docs), some with no positives, some all-positive."""
    indexes = np.sort(_rng.integers(0, n_queries, n)).astype(np.int32)
    preds = _rng.uniform(size=(n,)).astype(np.float32)
    target = (_rng.uniform(size=(n,)) < 0.3).astype(np.int32)
    # force one all-negative and one all-positive query
    target[indexes == 0] = 0
    target[indexes == 1] = 1
    return jnp.asarray(preds), jnp.asarray(target), jnp.asarray(indexes)


@pytest.mark.parametrize("metric_cls,kwargs", METRICS, ids=lambda x: getattr(x, "__name__", str(x)))
@pytest.mark.parametrize("action", ["neg", "pos", "skip"])
def test_segmented_matches_eager(metric_cls, kwargs, action):
    preds, target, indexes = _fixture()
    eager = metric_cls(empty_target_action=action, **kwargs)
    compiled = metric_cls(empty_target_action=action, max_queries=16, max_docs_per_query=64, **kwargs)
    eager.update(preds, target, indexes=indexes)
    compiled.update(preds, target, indexes=indexes)
    np.testing.assert_allclose(float(compiled.compute()), float(eager.compute()), rtol=1e-5, atol=1e-7)


def test_graded_ndcg_segmented():
    n = 120
    indexes = jnp.asarray(np.sort(_rng.integers(0, 10, n)).astype(np.int32))
    preds = jnp.asarray(_rng.uniform(size=(n,)).astype(np.float32))
    target = jnp.asarray(_rng.integers(0, 4, n).astype(np.int32))  # graded relevance
    eager = RetrievalNormalizedDCG(k=5)
    compiled = RetrievalNormalizedDCG(k=5, max_queries=12, max_docs_per_query=32)
    eager.update(preds, target, indexes=indexes)
    compiled.update(preds, target, indexes=indexes)
    np.testing.assert_allclose(float(compiled.compute()), float(eager.compute()), rtol=1e-5)


def test_fully_compiled_update_and_compute():
    """buffer_capacity + static bounds: update_state AND compute_state jit."""
    preds, target, indexes = _fixture()
    m = RetrievalMAP(max_queries=16, max_docs_per_query=64, buffer_capacity=256)
    state = m.init_state()
    state = jax.jit(m.update_state)(state, preds, target, indexes=indexes)

    @jax.jit
    def compiled_compute(s):
        return m.compute_state(s)

    got = float(compiled_compute(state))
    eager = RetrievalMAP()
    eager.update(preds, target, indexes=indexes)
    np.testing.assert_allclose(got, float(eager.compute()), rtol=1e-6)


def test_segmented_overflow_raises_eagerly():
    preds, target, indexes = _fixture()
    m = RetrievalMAP(max_queries=4, max_docs_per_query=4)  # way too small
    m.update(preds, target, indexes=indexes)
    with pytest.raises(MetricsUserError, match="static bounds"):
        m.compute()


def test_segmented_overflow_nan_under_jit():
    preds, target, indexes = _fixture()
    m = RetrievalMAP(max_queries=4, max_docs_per_query=4, buffer_capacity=256)
    state = m.update_state(m.init_state(), preds, target, indexes=indexes)
    out = jax.jit(m.compute_state)(state)
    assert np.isnan(float(out))


def test_error_action_incompatible_with_compiled():
    with pytest.raises(ValueError, match="incompatible"):
        RetrievalMAP(empty_target_action="error", max_queries=8, max_docs_per_query=8)


def test_bounds_must_come_together():
    with pytest.raises(ValueError, match="together"):
        RetrievalMAP(max_queries=8)


def test_buffer_overflow_poisons_compiled_compute():
    """Review regression: a buffer whose count outran its capacity inside jit
    must not be silently scored by the compiled path."""
    preds, target, indexes = _fixture()
    m = RetrievalMAP(max_queries=16, max_docs_per_query=64, buffer_capacity=16)
    state = m.init_state()
    step = jax.jit(m.update_state)
    for i in range(0, 160, 32):
        state = step(state, preds[i : i + 32], target[i : i + 32], indexes=indexes[i : i + 32])
    # traced compute -> NaN
    assert np.isnan(float(jax.jit(m.compute_state)(state)))
    # eager compute -> raise
    with pytest.raises(MetricsUserError, match="buffer_capacity"):
        m.compute_state(state)
