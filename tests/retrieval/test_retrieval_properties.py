"""Property tests for the single-query retrieval scorers (hypothesis).

Bounds and monotonicity that hold by definition: recall@k and hit-rate@k are
nondecreasing in k, every rate lives in [0, 1], perfect rankings score 1, and
the now-traceable scorers agree between eager and vmapped execution on
hypothesis-generated queries (not just the fixture corpus).
"""
import numpy as np
import pytest

pytest.importorskip("hypothesis", reason="property-based tests need the `test` extra (pip install metrics-tpu[test])")
from hypothesis import HealthCheck, given, settings
from hypothesis import strategies as st
from hypothesis.extra.numpy import arrays

import jax
import jax.numpy as jnp

from metrics_tpu.ops import (
    retrieval_average_precision,
    retrieval_fall_out,
    retrieval_hit_rate,
    retrieval_precision,
    retrieval_r_precision,
    retrieval_recall,
    retrieval_reciprocal_rank,
)

SETTINGS = settings(
    max_examples=25,
    deadline=None,
    suppress_health_check=[HealthCheck.too_slow],
    derandomize=True,
)

N_DOCS = 8
preds_strategy = arrays(
    np.float32, (N_DOCS,), elements=st.floats(min_value=0, max_value=1, allow_nan=False, width=32), unique=True
)
target_strategy = arrays(np.bool_, (N_DOCS,), elements=st.booleans())


@SETTINGS
@given(preds=preds_strategy, target=target_strategy)
def test_recall_and_hit_rate_monotone_in_k(preds, target):
    p, t = jnp.asarray(preds), jnp.asarray(target)
    recalls = [float(retrieval_recall(p, t, k=k)) for k in range(1, N_DOCS + 1)]
    hits = [float(retrieval_hit_rate(p, t, k=k)) for k in range(1, N_DOCS + 1)]
    assert all(b >= a - 1e-7 for a, b in zip(recalls, recalls[1:]))
    assert all(b >= a - 1e-7 for a, b in zip(hits, hits[1:]))
    if target.any():
        assert recalls[-1] == pytest.approx(1.0)  # full depth recovers everything


@SETTINGS
@given(preds=preds_strategy, target=target_strategy)
def test_all_scorers_bounded(preds, target):
    p, t = jnp.asarray(preds), jnp.asarray(target)
    for fn, kwargs in [
        (retrieval_average_precision, {}),
        (retrieval_reciprocal_rank, {}),
        (retrieval_precision, {"k": 3}),
        (retrieval_recall, {"k": 3}),
        (retrieval_hit_rate, {"k": 3}),
        (retrieval_fall_out, {"k": 3}),
        (retrieval_r_precision, {}),
    ]:
        value = float(fn(p, t, **kwargs))
        assert 0.0 <= value <= 1.0 + 1e-6, fn.__name__


@SETTINGS
@given(target=target_strategy)
def test_perfect_ranking_scores_one(target):
    if not target.any():
        return
    # scores equal to relevance (plus rank-breaking noise below the gap)
    preds = jnp.asarray(target.astype(np.float32) + np.linspace(0, 0.4, N_DOCS, dtype=np.float32))
    t = jnp.asarray(target)
    assert float(retrieval_average_precision(preds, t)) == pytest.approx(1.0)
    assert float(retrieval_reciprocal_rank(preds, t)) == pytest.approx(1.0)
    assert float(retrieval_r_precision(preds, t)) == pytest.approx(1.0)


@SETTINGS
@given(preds=preds_strategy, target=target_strategy)
def test_vmapped_equals_eager_on_random_queries(preds, target):
    p = jnp.stack([jnp.asarray(preds), jnp.asarray(preds)[::-1]])
    t = jnp.stack([jnp.asarray(target), jnp.asarray(target)[::-1]])
    batched = jax.vmap(retrieval_average_precision)(p, t)
    eager = [float(retrieval_average_precision(p[i], t[i])) for i in range(2)]
    np.testing.assert_allclose(np.asarray(batched), eager, atol=1e-6)
