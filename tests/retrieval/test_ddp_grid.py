"""Retrieval metric × ddp cross: the reference's missing axis here.

Reference analog: every reference retrieval test file runs its class metric
with ddp=[True, False] through RetrievalMetricTester
(tests/retrieval/helpers.py:150-250). The hard property the world merge must
preserve is that a query's documents may be scattered across ranks — the
per-query grouping only becomes complete after the cat-state gather. Docs are
dealt round-robin so every query spans all ranks.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import metrics_tpu as M
from tests.helpers.testers import merge_world
from tests.retrieval.test_option_grid import _GRID, _fixture, _oracle

WORLD = 4


@pytest.mark.parametrize("empty_action", ["skip", "neg", "pos"])
@pytest.mark.parametrize("with_ignore", [False, True], ids=["plain", "ignore-index"])
@pytest.mark.parametrize("name,kwargs,per_query", _GRID, ids=[g[0] for g in _GRID])
def test_ddp_grid_vs_numpy_oracle(name, kwargs, per_query, empty_action, with_ignore):
    indexes, preds, target = _fixture(with_ignore, with_empty=True)
    if name == "RetrievalFallOut":
        target = target.copy()
        target[indexes == 5] = 1  # fall-out degenerates on all-positive queries

    ignore_index = -1 if with_ignore else None
    ranks = []
    for r in range(WORLD):
        m = getattr(M, name)(empty_target_action=empty_action, ignore_index=ignore_index, **kwargs)
        sel = slice(r, None, WORLD)  # round-robin: queries span every rank
        m.update(jnp.asarray(preds[sel]), jnp.asarray(target[sel]), indexes=jnp.asarray(indexes[sel]))
        ranks.append(m)
    got = float(merge_world(ranks).compute())

    want = _oracle(name, per_query, indexes, preds, target, empty_action, ignore_index)
    np.testing.assert_allclose(got, want, atol=1e-5)


@pytest.mark.parametrize("name,kwargs,per_query", _GRID, ids=[g[0] for g in _GRID])
def test_ddp_two_step_updates_match_single(name, kwargs, per_query):
    """Two updates per rank == one update per rank == single-process, for the
    same multiset of (index, pred, target) rows."""
    indexes, preds, target = _fixture(with_ignore=False, with_empty=False)

    def value(n_ranks, n_chunks):
        ranks = []
        for r in range(n_ranks):
            m = getattr(M, name)(**kwargs)
            rows = np.flatnonzero(np.arange(len(indexes)) % n_ranks == r)
            for chunk in np.array_split(rows, n_chunks):
                if chunk.size:
                    m.update(
                        jnp.asarray(preds[chunk]), jnp.asarray(target[chunk]), indexes=jnp.asarray(indexes[chunk])
                    )
            ranks.append(m)
        return float(merge_world(ranks).compute())

    single = value(1, 1)
    np.testing.assert_allclose(value(WORLD, 1), single, atol=1e-6)
    np.testing.assert_allclose(value(WORLD, 3), single, atol=1e-6)
