"""DDP grid for wrappers — child-metric states through the gather path.

Reference parity: reference wrapper tests run under ddp via testers.py:398-439
(tests/wrappers/test_minmax.py, test_multioutput.py). Wrapper state lives
partly in the wrapper (MinMax min/max extremes) and partly in child metrics
(Multioutput per-output clones, ClasswiseWrapper's base, MinMax's base), so
the merge fold must recurse into children — ``merge_world`` does, via
``_deep_snapshot`` order.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import metrics_tpu as M
from tests.helpers.testers import merge_world

WORLD = 4
N = 32

_rng = np.random.default_rng(31)
_P = _rng.random((N,)).astype(np.float32)
_T = _rng.random((N,)).astype(np.float32)
_P2 = _rng.random((N, 3)).astype(np.float32)
_T2 = _rng.random((N, 3)).astype(np.float32)
_PROBS = _rng.dirichlet(np.ones(4), size=N).astype(np.float32)
_LABELS = _rng.integers(0, 4, N)


def _shard(a, r):
    return jnp.asarray(a[r::WORLD])


def test_multioutput_ddp_merge_equals_single_process():
    single = M.MultioutputWrapper(M.MeanSquaredError(), num_outputs=3)
    single.update(jnp.asarray(_P2), jnp.asarray(_T2))
    want = single.compute()

    ranks = [M.MultioutputWrapper(M.MeanSquaredError(), num_outputs=3) for _ in range(WORLD)]
    for r in range(WORLD):
        ranks[r].update(_shard(_P2, r), _shard(_T2, r))
    got = merge_world(ranks).compute()

    np.testing.assert_allclose(np.asarray(got, np.float64), np.asarray(want, np.float64), atol=1e-6)
    # and against the direct per-output oracle
    oracle = ((_P2 - _T2) ** 2).mean(axis=0)
    np.testing.assert_allclose(np.asarray(got, np.float64), oracle, atol=1e-5)


def test_classwise_ddp_merge_equals_single_process():
    def make():
        return M.ClasswiseWrapper(M.Accuracy(num_classes=4, average="none"))

    single = make()
    single.update(jnp.asarray(_PROBS), jnp.asarray(_LABELS))
    want = single.compute()

    ranks = [make() for _ in range(WORLD)]
    for r in range(WORLD):
        ranks[r].update(_shard(_PROBS, r), _shard(_LABELS, r))
    got = merge_world(ranks).compute()

    assert set(got) == set(want)
    for k in want:
        np.testing.assert_allclose(float(got[k]), float(want[k]), atol=1e-6)


def test_minmax_ddp_merge():
    """Per-rank extremes fold with min/max tags; the child metric's state folds
    with sum tags — both against hand-computed expectations (a single process
    observes different intermediate compute() values, so THE invariant is the
    fold, not sequence equality)."""
    ranks = [M.MinMaxMetric(M.MeanSquaredError()) for _ in range(WORLD)]
    rank_extremes = []
    for r in range(WORLD):
        p, t = _shard(_P, r), _shard(_T, r)
        half = p.shape[0] // 2
        ranks[r](p[:half], t[:half])   # forward: updates base AND min/max
        ranks[r](p[half:], t[half:])
        rank_extremes.append((float(ranks[r].min_val), float(ranks[r].max_val)))

    merged = merge_world(ranks)
    got = merged.compute()

    want_min = min(lo for lo, _ in rank_extremes)
    want_max = max(hi for _, hi in rank_extremes)
    np.testing.assert_allclose(float(got["min"]), want_min, atol=1e-6)
    np.testing.assert_allclose(float(got["max"]), want_max, atol=1e-6)
    # merged child == all-data MSE
    np.testing.assert_allclose(float(got["raw"]), ((_P - _T) ** 2).mean(), atol=1e-5)


def test_bootstrap_ddp_merge():
    """Replica states are sum-tagged, so the world fold must equal combining
    each rank's resampled streams; the expectation is computed directly from
    the per-rank replica states."""
    B = 4

    def make():
        return M.BootStrapper(M.MeanSquaredError(), num_bootstraps=B, seed=7)

    ranks = [make() for _ in range(WORLD)]
    for r in range(WORLD):
        ranks[r].update(_shard(_P, r), _shard(_T, r))

    # expected per-replica moments: sum over ranks of each replica's state
    sums = np.zeros(B)
    totals = np.zeros(B)
    for r in ranks:
        sums += np.asarray(r.sum_squared_error, dtype=np.float64)
        totals += np.asarray(r.total, dtype=np.float64)
    expected_means = sums / totals

    got = merge_world(ranks).compute()
    np.testing.assert_allclose(float(got["mean"]), expected_means.mean(), atol=1e-6)
    np.testing.assert_allclose(
        float(got["std"]), expected_means.std(ddof=1), atol=1e-6,
    )


def test_bootstrap_ddp_raw_replicas():
    """raw=True exposes the per-replica values after the fold."""
    B = 4
    ranks = [
        M.BootStrapper(M.MeanSquaredError(), num_bootstraps=B, seed=11, raw=True)
        for _ in range(WORLD)
    ]
    for r in range(WORLD):
        ranks[r].update(_shard(_P, r), _shard(_T, r))
    got = merge_world(ranks).compute()
    assert np.asarray(got["raw"]).shape == (B,)
    assert np.isfinite(np.asarray(got["raw"])).all()
