"""Wrapper × buffer_capacity × ddp cross, and cat-state dist_sync_on_step.

Closes the remaining grid cells the reference covers through its ddp
parametrization of wrapper tests (tests/wrappers/* with testers.py:398-439):
a *buffered* cat-state child (``buffer_capacity`` turns the unbounded list
state into a fixed-capacity jittable CatBuffer) flowing through every wrapper
under the world merge, plus the cat-state sync==merge equivalence that stands
in for ``dist_sync_on_step`` on eager-compute curve metrics. Curve forward
under ``dist_sync_on_step`` is owned by
tests/classification/test_curve_dist_sync.py.
"""
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import average_precision_score, roc_auc_score

import metrics_tpu as M
from tests.helpers.testers import merge_world

WORLD = 4
N = 64  # total samples; per-rank stream = N // WORLD

_rng = np.random.default_rng(77)
_SCORES = _rng.random(N).astype(np.float32)
_LABELS = _rng.integers(0, 2, N)

CAPS = [None, 8, 64]  # None = plain list state; 8 forces eager buffer growth


def _shard(a, r):
    return jnp.asarray(a[r::WORLD])


def _ranks_updated(make):
    ranks = [make() for _ in range(WORLD)]
    for r in range(WORLD):
        p, t = _shard(_SCORES, r), _shard(_LABELS, r)
        half = p.shape[0] // 2
        ranks[r].update(p[:half], t[:half])
        ranks[r].update(p[half:], t[half:])
    return ranks


_SK_AUROC_ALL = roc_auc_score(_LABELS, _SCORES)


@pytest.mark.parametrize("cap", CAPS, ids=["list", "cap8", "cap64"])
def test_minmax_buffered_child_ddp(cap):
    """MinMax over a buffered AUROC: world merge == all-data sklearn value."""
    make = lambda: M.MinMaxMetric(M.AUROC(buffer_capacity=cap))
    got = merge_world(_ranks_updated(make)).compute()
    np.testing.assert_allclose(float(got["raw"]), _SK_AUROC_ALL, atol=1e-6)
    # one lifetime value -> min == max == raw
    np.testing.assert_allclose(float(got["min"]), float(got["max"]), atol=1e-6)
    np.testing.assert_allclose(float(got["min"]), float(got["raw"]), atol=1e-6)


@pytest.mark.parametrize("cap", CAPS, ids=["list", "cap8", "cap64"])
def test_multioutput_buffered_child_ddp(cap):
    """Per-output buffered cat states through the clone-per-output wrapper."""
    scores2 = np.stack([_SCORES, 1.0 - _SCORES], axis=1)
    labels2 = np.stack([_LABELS, _LABELS], axis=1)

    make = lambda: M.MultioutputWrapper(M.AUROC(buffer_capacity=cap), num_outputs=2)
    ranks = [make() for _ in range(WORLD)]
    for r in range(WORLD):
        ranks[r].update(jnp.asarray(scores2[r::WORLD]), jnp.asarray(labels2[r::WORLD]))
    got = np.asarray(merge_world(ranks).compute())
    want = [roc_auc_score(_LABELS, _SCORES), roc_auc_score(_LABELS, 1.0 - _SCORES)]
    np.testing.assert_allclose(got, want, atol=1e-6)


@pytest.mark.parametrize("cap", CAPS, ids=["list", "cap8", "cap64"])
def test_tracker_buffered_child_ddp(cap):
    """Tracker epochs over buffered AUROC under the world merge: per-epoch
    values and best_metric must match the per-epoch sklearn oracle."""
    epochs = [
        (_SCORES, _LABELS),
        (np.where(_LABELS == 1, _SCORES + 1.0, _SCORES).astype(np.float32), _LABELS),  # better epoch
    ]
    ranks = [M.MetricTracker(M.AUROC(buffer_capacity=cap)) for _ in range(WORLD)]
    for scores, labels in epochs:
        for r in range(WORLD):
            ranks[r].increment()
            ranks[r].update(jnp.asarray(scores[r::WORLD]), jnp.asarray(labels[r::WORLD]))
        # fold THIS epoch's child state across ranks into rank 0 (per-epoch
        # sync; the tracker itself is a history container, not a Metric)
        merge_world([r._metrics[-1] for r in ranks])
    tracker = ranks[0]
    want = [roc_auc_score(l, s) for s, l in epochs]
    got = [float(v) for v in tracker.compute_all()]
    np.testing.assert_allclose(got, want, atol=1e-6)
    best, which = tracker.best_metric(return_step=True)
    np.testing.assert_allclose(float(best), max(want), atol=1e-6)
    assert which == int(np.argmax(want))


@pytest.mark.parametrize("cap", [None, 64], ids=["list", "cap64"])
def test_bootstrap_buffered_child_ddp(cap):
    """Bootstrap replicas over a buffered child survive the world fold: raw
    per-replica values are real AUROCs of resampled streams (finite, in
    [0, 1]) and mean tracks the all-data value within resampling noise."""
    B = 8
    make = lambda: M.BootStrapper(M.AUROC(buffer_capacity=cap), num_bootstraps=B, seed=5, raw=True)
    got = merge_world(_ranks_updated(make)).compute()
    raw = np.asarray(got["raw"], np.float64)
    assert raw.shape == (B,)
    assert np.isfinite(raw).all() and (raw >= 0).all() and (raw <= 1).all()
    assert abs(float(got["mean"]) - _SK_AUROC_ALL) < 0.15
    np.testing.assert_allclose(float(got["mean"]), raw.mean(), atol=1e-6)
    np.testing.assert_allclose(float(got["std"]), raw.std(ddof=1), atol=1e-6)


# binned-curve forward under dist_sync_on_step lives in
# tests/classification/test_curve_dist_sync.py (single owner of that cell);
# this file keeps only the buffer_capacity-specific cross below.
@pytest.mark.parametrize("cap", [None, 16], ids=["list", "cap16"])
@pytest.mark.parametrize(
    "metric_cls,sk_fn",
    [(M.AUROC, roc_auc_score), (M.AveragePrecision, average_precision_score)],
    ids=["auroc", "average_precision"],
)
def test_curve_family_step_sync_merge_equivalence(metric_cls, sk_fn, cap):
    """Unbinned cat-state curves compute eagerly by design (data-dependent
    output shapes), so their dist_sync_on_step semantic is expressed through
    the documented sync == merge equivalence: the value of THIS step's batch
    across all ranks = compute(merge(per-rank batch states))."""
    rank_metrics = []
    for r in range(WORLD):
        m = metric_cls(buffer_capacity=cap)
        m.update(jnp.asarray(_SCORES[r::WORLD]), jnp.asarray(_LABELS[r::WORLD]))
        rank_metrics.append(m)
    got = float(merge_world(rank_metrics).compute())
    np.testing.assert_allclose(got, sk_fn(_LABELS, _SCORES), atol=1e-6)
