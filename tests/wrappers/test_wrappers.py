"""Wrapper tests (reference parity: tests/wrappers/*)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import Accuracy, MeanSquaredError, MetricCollection
from metrics_tpu.wrappers import BootStrapper, ClasswiseWrapper, MetricTracker, MinMaxMetric, MultioutputWrapper

_rng = np.random.default_rng(21)


def test_bootstrapper_mean_close_to_base():
    preds = jnp.asarray(_rng.integers(0, 5, 200))
    target = jnp.asarray(_rng.integers(0, 5, 200))
    base = Accuracy(num_classes=5)
    base.update(preds, target)
    boot = BootStrapper(Accuracy(num_classes=5), num_bootstraps=50, seed=0)
    boot.update(preds, target)
    out = boot.compute()
    assert set(out) == {"mean", "std"}
    assert abs(float(out["mean"]) - float(base.compute())) < 0.05
    assert float(out["std"]) > 0


def test_bootstrapper_quantile_raw():
    boot = BootStrapper(MeanSquaredError(), num_bootstraps=10, quantile=0.5, raw=True, seed=1)
    boot.update(jnp.asarray(_rng.random(64)), jnp.asarray(_rng.random(64)))
    out = boot.compute()
    assert out["raw"].shape == (10,)
    assert "quantile" in out


def test_bootstrapper_rejects_non_metric():
    with pytest.raises(ValueError, match="base metric"):
        BootStrapper(lambda x: x)


def test_classwise_wrapper_keys_and_values():
    m = ClasswiseWrapper(Accuracy(num_classes=3, average="none"), labels=["horse", "fish", "dog"])
    preds = jnp.asarray(_rng.random((10, 3)), dtype=jnp.float32)
    target = jnp.asarray(_rng.integers(0, 3, 10))
    out = m(preds, target)
    assert set(out) == {"accuracy_horse", "accuracy_fish", "accuracy_dog"}
    plain = Accuracy(num_classes=3, average="none")
    plain.update(preds, target)
    np.testing.assert_allclose(
        np.asarray([out["accuracy_horse"], out["accuracy_fish"], out["accuracy_dog"]]),
        np.asarray(plain.compute()),
        atol=1e-6,
    )


def test_classwise_in_collection_flattens():
    col = MetricCollection({"acc": ClasswiseWrapper(Accuracy(num_classes=3, average="none"))})
    preds = jnp.asarray(_rng.random((10, 3)), dtype=jnp.float32)
    target = jnp.asarray(_rng.integers(0, 3, 10))
    col.update(preds, target)
    res = col.compute()
    assert set(res) == {"accuracy_0", "accuracy_1", "accuracy_2"}


def test_minmax_tracks():
    mm = MinMaxMetric(MeanSquaredError())
    t = jnp.asarray([1.0, 2.0, 3.0])
    out1 = mm(t + 0.5, t)
    assert float(out1["min"]) == float(out1["max"]) == float(out1["raw"]) == pytest.approx(0.25)
    mm.update(t + 1.0, t)
    out2 = mm.compute()
    assert float(out2["max"]) > 0.25
    assert float(out2["min"]) == pytest.approx(0.25)


def test_multioutput_wrapper():
    m = MultioutputWrapper(MeanSquaredError(), num_outputs=2)
    preds = jnp.asarray(_rng.random((16, 2)), dtype=jnp.float32)
    target = jnp.asarray(_rng.random((16, 2)), dtype=jnp.float32)
    m.update(preds, target)
    res = np.asarray(m.compute())
    expected = [np.mean((np.asarray(preds)[:, i] - np.asarray(target)[:, i]) ** 2) for i in range(2)]
    np.testing.assert_allclose(res, expected, atol=1e-6)


def test_multioutput_removes_nan_rows():
    m = MultioutputWrapper(MeanSquaredError(), num_outputs=2)
    preds = np.asarray([[1.0, 1.0], [np.nan, 2.0], [3.0, 3.0]], dtype=np.float32)
    target = np.asarray([[1.0, 2.0], [2.0, 2.0], [3.0, 4.0]], dtype=np.float32)
    m.update(jnp.asarray(preds), jnp.asarray(target))
    res = np.asarray(m.compute())
    np.testing.assert_allclose(res[0], 0.0, atol=1e-6)  # nan row dropped for output 0
    np.testing.assert_allclose(res[1], np.mean((preds[:, 1] - target[:, 1]) ** 2), atol=1e-6)


def test_tracker():
    tracker = MetricTracker(MeanSquaredError(), maximize=False)
    t = jnp.asarray(_rng.random(32), dtype=jnp.float32)
    for shift in [0.5, 0.1, 0.3]:
        tracker.increment()
        tracker.update(t + shift, t)
    all_vals = np.asarray(tracker.compute_all())
    assert all_vals.shape == (3,)
    best_val, best_step = tracker.best_metric(return_step=True)  # (value, step): reference order
    assert best_step == 1
    assert best_val == pytest.approx(0.01, abs=1e-5)


def test_tracker_requires_increment():
    tracker = MetricTracker(MeanSquaredError())
    with pytest.raises(ValueError, match="increment"):
        tracker.update(jnp.asarray([1.0]), jnp.asarray([1.0]))


def test_tracker_with_collection():
    tracker = MetricTracker(MetricCollection({"mse": MeanSquaredError()}), maximize=[False])
    tracker.increment()
    tracker.update(jnp.asarray([1.0, 2.0]), jnp.asarray([1.0, 2.0]))
    res = tracker.compute_all()
    assert "mse" in res
    best = tracker.best_metric()
    assert best["mse"] == pytest.approx(0.0)


def test_minmax_forward_accumulates():
    """Regression: forward() must not wipe child-metric state (deep snapshot)."""
    mm = MinMaxMetric(MeanSquaredError())
    t = jnp.zeros(4)
    mm(t + 1.0, t)
    mm(t + 0.0, t)
    out = mm.compute()
    assert float(out["raw"]) == pytest.approx(0.5, abs=1e-6)


def test_bootstrapper_forward_accumulates():
    """Regression: forward() must not wipe the bootstrap copies' state."""
    bs = BootStrapper(MeanSquaredError(), num_bootstraps=8, seed=0)
    t = jnp.zeros(16)
    bs(t + 1.0, t)
    bs(t + 0.0, t)
    assert float(bs.compute()["mean"]) == pytest.approx(0.5, abs=0.25)


def test_tracker_rejects_maximize_list_for_single_metric():
    with pytest.raises(ValueError, match="MetricCollection"):
        MetricTracker(MeanSquaredError(), maximize=[False])


def test_classwise_forward_invalidates_cache():
    """Regression: compute() after forward() must not return a stale cache."""
    from metrics_tpu import Accuracy

    m = ClasswiseWrapper(Accuracy(num_classes=3, average=None))
    p1 = jnp.asarray([[0.8, 0.1, 0.1], [0.1, 0.8, 0.1]])
    t1 = jnp.asarray([0, 1])
    m.update(p1, t1)
    first = m.compute()
    m(jnp.asarray([[0.8, 0.1, 0.1]]), jnp.asarray([1]))  # forward: acc_1 drops
    second = m.compute()
    assert float(second["accuracy_1"]) == pytest.approx(0.5)
    assert float(first["accuracy_1"]) == pytest.approx(1.0)


def test_multioutput_forward_invalidates_cache():
    m = MultioutputWrapper(MeanSquaredError(), num_outputs=2)
    p = jnp.asarray([[1.0, 2.0]])
    t = jnp.asarray([[1.0, 2.0]])
    m.update(p, t)
    assert np.allclose(np.asarray(m.compute()), [0.0, 0.0])
    m(p + 1.0, t)  # forward adds per-output squared error of 1.0
    np.testing.assert_allclose(np.asarray(m.compute()), [0.5, 0.5], atol=1e-6)


def test_bootstrapper_vmap_path_active():
    """TPU redesign (SURVEY.md §7 build order 6): stacked state, no copies."""
    from metrics_tpu import MeanSquaredError

    bs = BootStrapper(MeanSquaredError(), num_bootstraps=6, seed=0)
    assert bs._vmapped and bs.metrics == []
    # state is one stacked pytree with a leading bootstrap axis
    assert all(getattr(bs, k).shape[0] == 6 for k in bs._defaults)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(32,)).astype(np.float32))
    y = x + 0.1
    bs.update(x, y)
    out = bs.compute()
    assert np.isfinite(float(out["mean"])) and np.isfinite(float(out["std"]))


@pytest.mark.parametrize("strategy", ["poisson", "multinomial"])
def test_bootstrapper_vmap_matches_copies_design(strategy):
    """Same seed => the stacked vmap path reproduces the reference's
    N-deepcopies design exactly (same host RNG draw order)."""
    from copy import deepcopy

    from metrics_tpu import MeanSquaredError

    rng = np.random.default_rng(5)
    batches = [(rng.normal(size=(16,)).astype(np.float32), rng.normal(size=(16,)).astype(np.float32)) for _ in range(3)]

    bs = BootStrapper(MeanSquaredError(), num_bootstraps=8, sampling_strategy=strategy, seed=11, raw=True)
    assert bs._vmapped
    # same wrapper forced onto the reference copies path
    bs_ref = BootStrapper(MeanSquaredError(), num_bootstraps=8, sampling_strategy=strategy, seed=11, raw=True)
    bs_ref._vmapped = False
    bs_ref.metrics = [deepcopy(MeanSquaredError()) for _ in range(8)]

    for x, y in batches:
        bs.update(jnp.asarray(x), jnp.asarray(y))
        bs_ref.update(jnp.asarray(x), jnp.asarray(y))
    got, want = bs.compute(), bs_ref.compute()
    np.testing.assert_allclose(np.asarray(got["raw"]), np.asarray(want["raw"]), rtol=1e-6)


def test_bootstrapper_update_rejects_tracing():
    from metrics_tpu import MeanSquaredError
    from metrics_tpu.utils.exceptions import MetricsUserError

    bs = BootStrapper(MeanSquaredError(), num_bootstraps=4, seed=0)
    with pytest.raises(MetricsUserError, match="resampling indices"):
        jax.jit(bs.update_state)(bs.init_state(), jnp.zeros((8,)), jnp.zeros((8,)))


def test_bootstrapper_inherits_base_state():
    """Review regression: replicas must start from the base metric's current
    (possibly pre-accumulated) state, like the deepcopy design."""
    base = MeanSquaredError()
    base.update(jnp.ones((4,)), jnp.zeros((4,)))  # sse=4, n=4
    bs = BootStrapper(base, num_bootstraps=3, seed=0, mean=True, std=False)
    assert bs._vmapped
    out = bs.compute()
    np.testing.assert_allclose(float(out["mean"]), 1.0)  # all replicas carry mse=1


def test_tracker_mixed_maximize_directions():
    """A collection tracked with per-metric directions: best step differs per
    metric when one is maximized and the other minimized."""
    from metrics_tpu import MeanAbsoluteError

    # the maximize list maps to the collection's SORTED key order
    # (collections.py:103, reference parity) — here ["mae", "mse"]
    tracker = MetricTracker(
        MetricCollection({"mse": MeanSquaredError(), "mae": MeanAbsoluteError()}),
        maximize=[True, False],  # maximize mae (artificially), minimize mse
    )
    t = jnp.asarray(_rng.random(32), dtype=jnp.float32)
    shifts = [0.5, 0.1, 0.3]
    for shift in shifts:
        tracker.increment()
        tracker.update(t + shift, t)
    best, steps = tracker.best_metric(return_step=True)
    # mse minimized -> the 0.1 epoch (step 1); mae maximized -> 0.5 (step 0)
    assert steps["mse"] == 1 and steps["mae"] == 0, steps
    assert best["mse"] == pytest.approx(0.01, abs=1e-5)
    assert best["mae"] == pytest.approx(0.5, abs=1e-5)


# ---- MultioutputWrapper option surface (reference wrappers/multioutput.py:83-115) --
def test_multioutput_remove_nans_per_output():
    """A NaN row is dropped only for the output where it appears."""
    from sklearn.metrics import mean_squared_error as sk_mse

    preds = np.asarray([[1.0, 10.0], [2.0, np.nan], [3.0, 30.0], [4.0, 40.0]], np.float32)
    target = np.asarray([[1.5, 11.0], [2.5, 21.0], [np.nan, 29.0], [4.0, 40.0]], np.float32)
    m = MultioutputWrapper(MeanSquaredError(), num_outputs=2)
    m.update(jnp.asarray(preds), jnp.asarray(target))
    got = np.asarray(m.compute())
    keep0 = ~np.isnan(preds[:, 0]) & ~np.isnan(target[:, 0])  # drops row 2
    keep1 = ~np.isnan(preds[:, 1]) & ~np.isnan(target[:, 1])  # drops row 1
    np.testing.assert_allclose(got[0], sk_mse(target[keep0, 0], preds[keep0, 0]), atol=1e-6)
    np.testing.assert_allclose(got[1], sk_mse(target[keep1, 1], preds[keep1, 1]), atol=1e-6)


def test_multioutput_remove_nans_disabled_propagates():
    preds = np.asarray([[1.0, 10.0], [2.0, np.nan]], np.float32)
    target = np.asarray([[1.0, 10.0], [2.0, 20.0]], np.float32)
    m = MultioutputWrapper(MeanSquaredError(), num_outputs=2, remove_nans=False)
    m.update(jnp.asarray(preds), jnp.asarray(target))
    got = np.asarray(m.compute())
    assert got[0] == 0.0 and np.isnan(got[1])


def test_multioutput_output_dim():
    """Outputs along dim 0 instead of the trailing dim."""
    preds = np.asarray([[1.0, 2.0, 3.0], [10.0, 20.0, 30.0]], np.float32)   # (2 outputs, 3 samples)
    target = np.asarray([[1.0, 2.0, 4.0], [10.0, 22.0, 30.0]], np.float32)
    m = MultioutputWrapper(MeanSquaredError(), num_outputs=2, output_dim=0)
    m.update(jnp.asarray(preds), jnp.asarray(target))
    got = np.asarray(m.compute())
    np.testing.assert_allclose(got[0], ((preds[0] - target[0]) ** 2).mean(), atol=1e-6)
    np.testing.assert_allclose(got[1], ((preds[1] - target[1]) ** 2).mean(), atol=1e-6)


def test_multioutput_squeeze_outputs_disabled_keeps_dim():
    """With squeeze_outputs=False each clone sees (N, 1) slices — metrics
    that accept 2D regression inputs must agree with the squeezed path."""
    rng = np.random.default_rng(5)
    preds = rng.random((8, 2)).astype(np.float32)
    target = rng.random((8, 2)).astype(np.float32)
    a = MultioutputWrapper(MeanSquaredError(), num_outputs=2)
    b = MultioutputWrapper(MeanSquaredError(), num_outputs=2, squeeze_outputs=False)
    a.update(jnp.asarray(preds), jnp.asarray(target))
    b.update(jnp.asarray(preds), jnp.asarray(target))
    np.testing.assert_allclose(np.asarray(a.compute()), np.asarray(b.compute()), atol=1e-6)


def test_multioutput_forward_returns_stacked_batch_values():
    preds = np.asarray([[1.0, 10.0], [2.0, 20.0]], np.float32)
    target = np.asarray([[1.0, 11.0], [2.0, 21.0]], np.float32)
    m = MultioutputWrapper(MeanSquaredError(), num_outputs=2)
    out = np.asarray(m(jnp.asarray(preds), jnp.asarray(target)))
    np.testing.assert_allclose(out, [0.0, 1.0], atol=1e-6)
