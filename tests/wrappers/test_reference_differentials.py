"""Deterministic wrapper behavior pinned against the live reference.

MinMaxMetric's min/max tracking across compute() calls, MetricTracker's
best_metric bookkeeping, and MultioutputWrapper's per-output slicing are
deterministic (BootStrapper is excluded: its resampling draws differ by
design). Reference: wrappers/minmax.py:23, tracker.py:26, multioutput.py:24.
"""
import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu as M


def _ref():
    from tests.conftest import reference_modular

    return reference_modular()


def test_minmax_tracking_vs_reference():
    torch, tm = _ref()
    ours = M.MinMaxMetric(M.MeanSquaredError())
    ref = tm.MinMaxMetric(tm.MeanSquaredError())
    rng = np.random.default_rng(51)
    for _ in range(4):  # min/max only move at compute() boundaries
        p = rng.random(16).astype(np.float32)
        t = rng.random(16).astype(np.float32)
        ours.update(jnp.asarray(p), jnp.asarray(t))
        ref.update(torch.tensor(p), torch.tensor(t))
        got, want = ours.compute(), ref.compute()
        for key in ("raw", "min", "max"):
            np.testing.assert_allclose(float(got[key]), float(want[key]), atol=1e-6, err_msg=key)


def test_tracker_best_metric_vs_reference():
    torch, tm = _ref()
    ours = M.MetricTracker(M.MeanSquaredError(), maximize=False)
    ref = tm.MetricTracker(tm.MeanSquaredError(), maximize=False)
    rng = np.random.default_rng(52)
    t = rng.random(32).astype(np.float32)
    for noise in (0.5, 0.1, 0.3):  # epoch 2 (index 1) is best
        ours.increment()
        ref.increment()
        p = (t + noise * rng.standard_normal(32)).astype(np.float32)
        ours.update(jnp.asarray(p), jnp.asarray(t))
        ref.update(torch.tensor(p), torch.tensor(t))
    np.testing.assert_allclose(
        np.asarray(ours.compute_all()), np.asarray(ref.compute_all()), atol=1e-6
    )
    ours_best, ours_idx = ours.best_metric(return_step=True)
    ref_best, ref_idx = ref.best_metric(return_step=True)
    np.testing.assert_allclose(float(ours_best), float(ref_best), atol=1e-6)
    assert int(ours_idx) == int(ref_idx)


@pytest.mark.parametrize("remove_nans", [True, False], ids=["remove_nans", "keep"])
def test_multioutput_vs_reference(remove_nans):
    torch, tm = _ref()
    preds = np.asarray([[1.0, 10.0], [2.0, np.nan], [3.0, 30.0]], np.float32)
    target = np.asarray([[1.5, 11.0], [2.5, 21.0], [3.5, 29.0]], np.float32)
    ours = M.MultioutputWrapper(M.MeanSquaredError(), num_outputs=2, remove_nans=remove_nans)
    ref = tm.MultioutputWrapper(tm.MeanSquaredError(), num_outputs=2, remove_nans=remove_nans)
    ours.update(jnp.asarray(preds), jnp.asarray(target))
    ref.update(torch.tensor(preds), torch.tensor(target))
    got = np.asarray(ours.compute())
    want = np.asarray([float(v) for v in ref.compute()])
    np.testing.assert_allclose(got, want, atol=1e-6)
