"""Wrapper behavior under the sync/unsync state machine — property tests.

Reference analog: tests/bases/test_ddp.py:135-241 (synced-save /
unsync-restore). The wrappers are the risky case because their state spans
the wrapper AND child metrics; sync must capture both, unsync must restore
both, and compute-under-sync must see the merged world.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import metrics_tpu as M
from metrics_tpu.utils.exceptions import MetricsUserError
from tests.helpers.testers import merge_world

_rng = np.random.default_rng(13)
_P = jnp.asarray(_rng.random(24).astype(np.float32))
_T = jnp.asarray(_rng.random(24).astype(np.float32))
_P2 = jnp.asarray(_rng.random((24, 2)).astype(np.float32))
_T2 = jnp.asarray(_rng.random((24, 2)).astype(np.float32))


@pytest.mark.parametrize(
    "make,args",
    [
        (lambda: M.MinMaxMetric(M.MeanSquaredError()), (_P, _T)),
        (lambda: M.MultioutputWrapper(M.MeanSquaredError(), num_outputs=2), (_P2, _T2)),
        (lambda: M.ClasswiseWrapper(M.Accuracy(num_classes=3, average="none")),
         (jnp.asarray(_rng.dirichlet(np.ones(3), 24).astype(np.float32)), jnp.asarray(_rng.integers(0, 3, 24)))),
        (lambda: M.BootStrapper(M.MeanSquaredError(), num_bootstraps=3, seed=3), (_P, _T)),
    ],
    ids=["minmax", "multioutput", "classwise", "bootstrap"],
)
class TestWrapperSyncStateMachine:
    def test_unsync_restores_deep_state(self, make, args):
        """sync (via a world merge) then unsync returns EVERY node — wrapper
        and children — to its pre-sync state."""
        m = make()
        if isinstance(m, M.MinMaxMetric):
            m(*args)  # forward also advances min/max
        else:
            m.update(*args)
        before = [(type(n).__name__, jnp.asarray(jnp.concatenate([jnp.ravel(jnp.asarray(v)) for v in st.values()]))
                   if st else None)
                  for (n, st, _) in m._deep_snapshot()]

        other = make()
        other.update(*args)

        # emulate the gather by merging the other rank in, then rolling back
        snap = m._deep_snapshot()
        merge_world([m, other])
        M.Metric._deep_restore(snap)

        after = [(type(n).__name__, jnp.asarray(jnp.concatenate([jnp.ravel(jnp.asarray(v)) for v in st.values()]))
                  if st else None)
                 for (n, st, _) in m._deep_snapshot()]
        for (name_b, flat_b), (name_a, flat_a) in zip(before, after):
            assert name_b == name_a
            if flat_b is not None:
                np.testing.assert_allclose(np.asarray(flat_a), np.asarray(flat_b), atol=1e-7)

    def test_double_unsync_guard(self, make, args):
        m = make()
        m.update(*args)
        with pytest.raises(MetricsUserError):
            m.unsync()

    def test_merge_is_idempotent_with_empty_rank(self, make, args):
        """Folding in a rank that saw no data must not change the value."""
        m1 = make()
        if isinstance(m1, M.MinMaxMetric):
            m1(*args)
        else:
            m1.update(*args)
        want = m1.compute()

        m2 = make()
        if isinstance(m2, M.MinMaxMetric):
            m2(*args)
        else:
            m2.update(*args)
        empty = make()
        got = merge_world([m2, empty]).compute()

        flat_w = np.concatenate([np.ravel(np.asarray(v, np.float64)) for v in jax.tree_util.tree_leaves(want)]) \
            if not isinstance(want, dict) else np.concatenate([np.ravel(np.asarray(want[k], np.float64)) for k in sorted(want)])
        flat_g = np.concatenate([np.ravel(np.asarray(v, np.float64)) for v in jax.tree_util.tree_leaves(got)]) \
            if not isinstance(got, dict) else np.concatenate([np.ravel(np.asarray(got[k], np.float64)) for k in sorted(got)])
        np.testing.assert_allclose(flat_g, flat_w, atol=1e-6)
