"""BootStrapper distributed semantics: cross-device sync IS a state merge.

The vmap-stacked bootstrap states register per-state reductions, so the same
``merge_states`` that powers collective sync must combine two workers' partial
bootstrap states into the state one worker would have produced seeing all the
data (up to resampling noise). Reference analog: N module copies each synced
like a normal metric (wrappers/bootstrapping.py:49).
"""
import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu import Accuracy, BootStrapper, MeanSquaredError


def _states(metric):
    return {name: getattr(metric, name) for name in metric._defaults}


def test_bootstrap_merge_matches_single_worker_accuracy():
    rng = np.random.default_rng(0)
    preds = rng.integers(0, 5, size=(4, 64)).astype(np.int32)
    target = np.where(rng.uniform(size=(4, 64)) < 0.7, preds, rng.integers(0, 5, size=(4, 64))).astype(np.int32)

    worker_a = BootStrapper(Accuracy(num_classes=5), num_bootstraps=32, seed=1)
    worker_b = BootStrapper(Accuracy(num_classes=5), num_bootstraps=32, seed=2)
    for i in range(2):
        worker_a.update(jnp.asarray(preds[i]), jnp.asarray(target[i]))
    for i in range(2, 4):
        worker_b.update(jnp.asarray(preds[i]), jnp.asarray(target[i]))

    merged = worker_a.merge_states(_states(worker_a), _states(worker_b))
    out = worker_a.compute_state(merged)

    global_acc = float((preds == target).mean())
    # the bootstrap mean over 32 resamples of all 256 samples concentrates
    # around the global accuracy; std stays small but positive
    assert out["mean"] == pytest.approx(global_acc, abs=0.05)
    assert 0.0 < float(out["std"]) < 0.1


def test_bootstrap_merge_is_commutative():
    rng = np.random.default_rng(3)
    preds = rng.normal(size=(4, 32)).astype(np.float32)
    target = preds + 0.1 * rng.normal(size=(4, 32)).astype(np.float32)

    worker_a = BootStrapper(MeanSquaredError(), num_bootstraps=16, seed=5)
    worker_b = BootStrapper(MeanSquaredError(), num_bootstraps=16, seed=6)
    for i in range(2):
        worker_a.update(jnp.asarray(preds[i]), jnp.asarray(target[i]))
    for i in range(2, 4):
        worker_b.update(jnp.asarray(preds[i]), jnp.asarray(target[i]))

    ab = worker_a.compute_state(worker_a.merge_states(_states(worker_a), _states(worker_b)))
    ba = worker_a.compute_state(worker_a.merge_states(_states(worker_b), _states(worker_a)))
    np.testing.assert_allclose(float(ab["mean"]), float(ba["mean"]), rtol=1e-6)
    np.testing.assert_allclose(float(ab["std"]), float(ba["std"]), rtol=1e-5)
