"""Parity suite for the heavy-kernel layer (``metrics_tpu/ops/kernels/``).

Three gates per kernel, all running on the tier-1 CPU lane:

* **Pallas-interpret vs jit reference** — ``use_pallas="force"`` runs the
  Pallas body in interpret mode off-TPU; matching/IoU outputs must be bitwise
  equal, float similarity is tolerance-bounded by matmul accumulation order.
* **jit reference vs pre-change eager** — the legacy einsum/per-image code
  the kernels replaced, reproduced inline (and, for mAP, the still-shipping
  ``device_state=False`` host-list path); bitwise.
* **recompile-count guards** — the trace-time counters in
  ``metrics_tpu.ops.kernels`` prove pow2 bucketing bounds the jit signature
  set: ragged streams retrace at most once per bucket, steady state retraces
  zero times.

Device-mode Pallas runs are ``@pytest.mark.pallas`` and skip off-TPU.
"""
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.ops import kernels as K
from metrics_tpu.ops.kernels import (
    BucketedFeatureExtractor,
    evaluate_matches,
    maybe_bucketed,
    next_pow2,
    pairwise_cosine_pr,
)

_ON_TPU = jax.default_backend() not in ("cpu", "gpu")


# --------------------------------------------------------------------------- #
# fixtures
# --------------------------------------------------------------------------- #
def _random_images(rng, n_images, max_det=9, max_gt=7, pad_det=16, pad_gt=8):
    """pow2-padded ragged detection/groundtruth buffers + counts."""

    def boxes(n, pad):
        xy = rng.uniform(0, 80, size=(pad, 2)).astype(np.float32)
        wh = rng.uniform(1, 40, size=(pad, 2)).astype(np.float32)
        out = np.concatenate([xy, xy + wh], axis=1)
        out[n:] = 0.0
        return out

    det_boxes, det_scores, det_labels, det_counts = [], [], [], []
    gt_boxes, gt_labels, gt_counts = [], [], []
    for _ in range(n_images):
        nd = int(rng.integers(0, max_det + 1))
        ng = int(rng.integers(0, max_gt + 1))
        det_boxes.append(boxes(nd, pad_det))
        scores = rng.uniform(0, 1, size=pad_det).astype(np.float32)
        scores[nd:] = 0.0
        det_scores.append(scores)
        lbl = rng.integers(0, 3, size=pad_det).astype(np.int32)
        lbl[nd:] = -1
        det_labels.append(lbl)
        det_counts.append(nd)
        gt_boxes.append(boxes(ng, pad_gt))
        glbl = rng.integers(0, 3, size=pad_gt).astype(np.int32)
        glbl[ng:] = -1
        gt_labels.append(glbl)
        gt_counts.append(ng)
    return dict(
        det_boxes=np.stack(det_boxes), det_scores=np.stack(det_scores),
        det_labels=np.stack(det_labels), det_counts=np.asarray(det_counts, np.int32),
        gt_boxes=np.stack(gt_boxes), gt_labels=np.stack(gt_labels),
        gt_counts=np.asarray(gt_counts, np.int32),
    )


_CLASS_IDS = np.array([0, 1, 2, 0], np.int32)
_CLASS_MASK = np.array([True, True, True, False])
_AREA_RANGES = np.array([[0.0, 1e10], [0.0, 1024.0], [1024.0, 9216.0], [9216.0, 1e10]], np.float32)
_THRESHOLDS = np.linspace(0.5, 0.95, 10).astype(np.float32)


def _eval_matches(batch, use_pallas):
    return evaluate_matches(
        **batch,
        class_ids=_CLASS_IDS, class_mask=_CLASS_MASK,
        area_ranges=_AREA_RANGES, thresholds=_THRESHOLDS,
        max_det=100, use_pallas=use_pallas,
    )


def _coco_lists(rng, n_images, n_classes=3):
    """Legacy-format COCO list inputs (ragged per image)."""
    preds, target = [], []
    for _ in range(n_images):
        nd = int(rng.integers(0, 8))
        ng = int(rng.integers(0, 6))

        def boxes(n):
            xy = rng.uniform(0, 80, size=(n, 2)).astype(np.float32)
            wh = rng.uniform(1, 40, size=(n, 2)).astype(np.float32)
            return np.concatenate([xy, xy + wh], axis=1)

        preds.append({
            "boxes": jnp.asarray(boxes(nd)),
            "scores": jnp.asarray(rng.uniform(0, 1, size=nd).astype(np.float32)),
            "labels": jnp.asarray(rng.integers(0, n_classes, size=nd).astype(np.int32)),
        })
        target.append({
            "boxes": jnp.asarray(boxes(ng)),
            "labels": jnp.asarray(rng.integers(0, n_classes, size=ng).astype(np.int32)),
        })
    return preds, target


# --------------------------------------------------------------------------- #
# iou_matching
# --------------------------------------------------------------------------- #
class TestIouMatchingKernel:
    def test_interpret_pallas_bitwise_equals_jit_reference(self, monkeypatch):
        monkeypatch.delenv("METRICS_TPU_PALLAS", raising=False)
        rng = np.random.default_rng(0)
        batch = _random_images(rng, 12)
        ref = _eval_matches(batch, "never")
        pal = _eval_matches(batch, "force")
        assert set(ref) == set(pal)
        for key in ref:
            np.testing.assert_array_equal(
                np.asarray(ref[key]), np.asarray(pal[key]), err_msg=key
            )

    def test_jit_reference_matches_legacy_per_image_eager(self):
        """The fused batch program vs the pre-change building blocks
        (``box_iou`` + ``match_image``) applied per image, eagerly."""
        from metrics_tpu.ops.detection.boxes import box_iou
        from metrics_tpu.ops.detection.matching import match_image

        rng = np.random.default_rng(1)
        batch = _random_images(rng, 6)
        out = _eval_matches(batch, "never")
        for i in range(6):
            nd = int(batch["det_counts"][i])
            ng = int(batch["gt_counts"][i])
            order = np.argsort(-batch["det_scores"][i][:nd], kind="stable")
            ious = np.zeros((batch["det_boxes"].shape[1], batch["gt_boxes"].shape[1]), np.float32)
            if nd and ng:
                ious[:nd, :ng] = np.asarray(
                    box_iou(batch["det_boxes"][i][:nd][order], batch["gt_boxes"][i][:ng])
                )
            labels_sorted = np.full(batch["det_labels"].shape[1], -1, np.int32)
            labels_sorted[:nd] = batch["det_labels"][i][:nd][order]
            det_class = (labels_sorted[None, :] == _CLASS_IDS[:, None]) & (
                np.arange(labels_sorted.size)[None, :] < nd
            ) & _CLASS_MASK[:, None]
            gt_class = (batch["gt_labels"][i][None, :] == _CLASS_IDS[:, None]) & (
                np.arange(batch["gt_labels"].shape[1])[None, :] < ng
            ) & _CLASS_MASK[:, None]
            gt_areas = (batch["gt_boxes"][i][:, 2] - batch["gt_boxes"][i][:, 0]) * (
                batch["gt_boxes"][i][:, 3] - batch["gt_boxes"][i][:, 1]
            )
            gt_area_ignore = (gt_areas[None, :] < _AREA_RANGES[:, :1]) | (
                gt_areas[None, :] > _AREA_RANGES[:, 1:]
            )
            legacy_matches, _ = match_image(
                jnp.asarray(ious), jnp.asarray(det_class), jnp.asarray(gt_class),
                jnp.asarray(gt_area_ignore), jnp.asarray(_THRESHOLDS),
            )
            np.testing.assert_array_equal(
                np.asarray(out["det_matches"])[i], np.asarray(legacy_matches), err_msg=f"image {i}"
            )

    def test_recompile_guard_same_shapes_trace_once(self):
        rng = np.random.default_rng(2)
        K.reset_trace_counts()
        for _ in range(5):
            _eval_matches(_random_images(rng, 4), "never")
        assert K.trace_counts().get("iou_matching", 0) <= 1

    @pytest.mark.pallas
    @pytest.mark.skipif(not _ON_TPU, reason="device-mode Pallas needs a real TPU")
    def test_device_pallas_bitwise_equals_jit_reference(self):
        rng = np.random.default_rng(3)
        batch = _random_images(rng, 8)
        ref = _eval_matches(batch, "never")
        pal = _eval_matches(batch, "force")
        for key in ref:
            np.testing.assert_array_equal(np.asarray(ref[key]), np.asarray(pal[key]), err_msg=key)


class TestMeanAPDeviceState:
    def test_device_state_bitwise_equals_legacy_host_lists(self):
        from metrics_tpu.detection import MeanAveragePrecision

        rng = np.random.default_rng(4)
        dev = MeanAveragePrecision(class_metrics=True)
        host = MeanAveragePrecision(class_metrics=True, device_state=False)
        assert dev.device_state and not host.device_state
        for _ in range(3):
            preds, target = _coco_lists(rng, 5)
            dev.update(preds, target)
            host.update(preds, target)
        got, want = dev.compute(), host.compute()
        assert set(got) == set(want)
        for key in want:
            np.testing.assert_array_equal(np.asarray(got[key]), np.asarray(want[key]), err_msg=key)

    def test_update_recompiles_bounded_by_pow2_buckets(self):
        """Ragged image-batch sizes (1..6) collapse to 3 pow2 buckets; the
        compiled update engine plus the matching kernel retrace at most once
        per bucket and not per distinct batch size."""
        from metrics_tpu.detection import MeanAveragePrecision

        rng = np.random.default_rng(5)
        K.reset_trace_counts()
        m = MeanAveragePrecision()
        sizes = [1, 2, 3, 4, 5, 6, 3, 5, 2, 6, 1, 4]
        for n in sizes:
            preds, target = _coco_lists(rng, n)
            m.update(preds, target)
        buckets = {next_pow2(n) for n in sizes}
        stats = m._update_engine.stats
        assert stats.cache_misses <= len(buckets), stats
        assert stats.cache_hits + stats.donated_calls > 0, stats
        m.compute()
        traced_after_first = K.trace_counts().get("iou_matching", 0)
        m.compute()  # steady state: no new kernel traces
        assert K.trace_counts().get("iou_matching", 0) == traced_after_first


# --------------------------------------------------------------------------- #
# cosine_matching
# --------------------------------------------------------------------------- #
def _random_embeddings(rng, b=3, l=1, p=7, r=5, d=16):
    pe = rng.normal(size=(b, l, p, d)).astype(np.float32)
    te = rng.normal(size=(b, l, r, d)).astype(np.float32)
    pe /= np.linalg.norm(pe, axis=-1, keepdims=True)
    te /= np.linalg.norm(te, axis=-1, keepdims=True)
    pw = rng.uniform(0.1, 1, size=(b, p)).astype(np.float32)
    tw = rng.uniform(0.1, 1, size=(b, r)).astype(np.float32)
    return jnp.asarray(pe), jnp.asarray(te), jnp.asarray(pw), jnp.asarray(tw)


@jax.jit
def _legacy_pr_f1(pe, te, pw, tw):
    """The pre-change ``_precision_recall_f1`` verbatim — including its
    ``jax.jit`` decoration, which fixes the fusion (and thus rounding) order
    the bitwise comparison pins."""
    cos_sim = jnp.einsum("blpd,blrd->blpr", pe, te)
    precision = jnp.einsum("bls,bs->bls", jnp.max(cos_sim, axis=3), pw).sum(-1)
    recall = jnp.einsum("bls,bs->bls", jnp.max(cos_sim, axis=2), tw).sum(-1)
    f1 = 2 * precision * recall / (precision + recall)
    f1 = jnp.where(jnp.isnan(f1), 0.0, f1)
    return precision.T.squeeze(), recall.T.squeeze(), f1.T.squeeze()


class TestCosineMatchingKernel:
    def test_jit_reference_bitwise_equals_legacy_eager(self):
        args = _random_embeddings(np.random.default_rng(6))
        got = pairwise_cosine_pr(*args, use_pallas="never")
        want = _legacy_pr_f1(*args)
        for g, w, name in zip(got, want, ("precision", "recall", "f1")):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(w), err_msg=name)

    def test_interpret_pallas_tolerance_bounded_vs_reference(self, monkeypatch):
        monkeypatch.delenv("METRICS_TPU_PALLAS", raising=False)
        args = _random_embeddings(np.random.default_rng(7))
        ref = pairwise_cosine_pr(*args, use_pallas="never")
        pal = pairwise_cosine_pr(*args, use_pallas="force")
        for g, w, name in zip(pal, ref, ("precision", "recall", "f1")):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w), rtol=1e-5, atol=1e-6, err_msg=name)

    def test_recompile_guard_same_shapes_trace_once(self):
        rng = np.random.default_rng(8)
        K.reset_trace_counts()
        for _ in range(4):
            pairwise_cosine_pr(*_random_embeddings(rng), use_pallas="never")
        assert K.trace_counts().get("cosine_matching", 0) <= 1

    def test_ops_text_bert_delegates_to_kernel(self):
        from metrics_tpu.ops.text.bert import _precision_recall_f1

        args = _random_embeddings(np.random.default_rng(9))
        got = _precision_recall_f1(*args)
        want = _legacy_pr_f1(*args)
        for g, w, name in zip(got, want, ("precision", "recall", "f1")):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(w), err_msg=name)

    @pytest.mark.pallas
    @pytest.mark.skipif(not _ON_TPU, reason="device-mode Pallas needs a real TPU")
    def test_device_pallas_tolerance_bounded(self):
        args = _random_embeddings(np.random.default_rng(10))
        ref = pairwise_cosine_pr(*args, use_pallas="never")
        pal = pairwise_cosine_pr(*args, use_pallas="force")
        for g, w in zip(pal, ref):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w), rtol=1e-4)


# --------------------------------------------------------------------------- #
# feature_extract
# --------------------------------------------------------------------------- #
class TestBucketedFeatureExtractor:
    def test_values_identical_and_signatures_bounded(self):
        shapes_seen = set()

        def feat(imgs):
            shapes_seen.add(tuple(imgs.shape))
            return imgs.reshape(imgs.shape[0], -1) * 2.0

        feat.row_independent = True
        wrapped = maybe_bucketed(feat, True)
        assert isinstance(wrapped, BucketedFeatureExtractor)
        rng = np.random.default_rng(11)
        for n in (1, 2, 3, 4, 5, 6, 7, 8, 3, 5, 7):
            imgs = jnp.asarray(rng.normal(size=(n, 2, 2)).astype(np.float32))
            np.testing.assert_array_equal(np.asarray(wrapped(imgs)), np.asarray(feat(imgs)))
        # ragged 1..8 collapses to pow2 batches {1,2,4,8} (+ the raw shapes the
        # parity recheck above added): the padded call set stays log-bounded
        padded = {s for s in shapes_seen if s[0] in (1, 2, 4, 8)}
        assert {s[0] for s in padded} <= {1, 2, 4, 8}

    def test_opt_outs(self):
        def frn(x):
            return x

        frn.row_independent = False
        assert maybe_bucketed(frn, True) is frn
        assert maybe_bucketed(None, True) is None

        def fr(x):
            return x

        assert maybe_bucketed(fr, False) is fr
        wrapped = maybe_bucketed(fr, True)
        assert maybe_bucketed(wrapped, True) is wrapped

    def test_multi_array_padding_lpips_style(self):
        def dist(a, b):
            return jnp.mean((a - b) ** 2, axis=(1, 2, 3))

        wrapped = maybe_bucketed(dist, True)
        rng = np.random.default_rng(12)
        a = jnp.asarray(rng.normal(size=(5, 3, 4, 4)).astype(np.float32))
        b = jnp.asarray(rng.normal(size=(5, 3, 4, 4)).astype(np.float32))
        np.testing.assert_array_equal(np.asarray(wrapped(a, b)), np.asarray(dist(a, b)))

    def test_attribute_delegation(self):
        class Net:
            row_independent = True
            num_features = 77

            def __call__(self, x):
                return x

        wrapped = maybe_bucketed(Net(), True)
        assert wrapped.num_features == 77


# --------------------------------------------------------------------------- #
# observability: tracer events + strict Prometheus exposition
# --------------------------------------------------------------------------- #
class TestHeavyKernelObservability:
    def test_dispatch_and_fallback_series_parse_strictly(self):
        from metrics_tpu.observability import to_prometheus_text
        from metrics_tpu.observability.instruments import get_registry
        from tests.observability.test_exporters import _StrictPromParser

        get_registry().clear()
        try:
            batch = _random_images(np.random.default_rng(13), 2)
            _eval_matches(batch, "never")
            K.record_fallback("iou_matching", "synthetic: exposition test")
            text = to_prometheus_text(get_registry())
            families, samples = _StrictPromParser().parse(text)
            by = {(n, tuple(sorted(l.items()))): v for n, l, v in samples}
            assert by[(
                "metrics_tpu_heavy_kernel_calls",
                (("impl", "jit"), ("kernel", "iou_matching")),
            )] >= 1.0
            assert by[(
                "metrics_tpu_heavy_kernel_fallbacks", (("kernel", "iou_matching"),)
            )] == 1.0
            assert families["metrics_tpu_heavy_kernel_bucket_width"]["type"] == "histogram"
            width_counts = [
                v for (n, labels), v in by.items()
                if n == "metrics_tpu_heavy_kernel_bucket_width_count"
                and dict(labels)["kernel"] == "iou_matching"
            ]
            assert width_counts and width_counts[0] >= 1.0
        finally:
            get_registry().clear()

    def test_kernel_dispatch_tracer_events(self):
        from metrics_tpu import observability as obs
        from metrics_tpu.observability.tracer import EVENT_CATALOG

        assert EVENT_CATALOG["kernel"] == ("kernel/dispatch", "kernel/fallback")
        with obs.trace() as tracer:
            _eval_matches(_random_images(np.random.default_rng(14), 2), "never")
        counts = tracer.counts_by_name()
        assert counts.get("kernel/dispatch", 0) >= 1
        event = next(e for e in tracer.events() if e.name == "kernel/dispatch")
        assert event.args["kernel"] == "iou_matching"
        assert event.args["impl"] == "jit"
        assert event.args["bucket_width"] == 16


# --------------------------------------------------------------------------- #
# registry hygiene
# --------------------------------------------------------------------------- #
class TestKernelRegistry:
    def test_registry_entries_are_importable_and_documented(self):
        import importlib

        for name, spec in K.KERNELS.items():
            assert spec.name == name
            mod = importlib.import_module(spec.module)
            assert mod is not None
            assert spec.description and spec.pallas_scope

    def test_resolve_use_pallas_modes(self, monkeypatch):
        monkeypatch.delenv("METRICS_TPU_PALLAS", raising=False)
        assert K.resolve_use_pallas("never") == (False, False)
        use, interpret = K.resolve_use_pallas("force")
        assert use and interpret == (not _ON_TPU)
        # plain auto never claims the pallas path off-TPU or mid-trace
        if not _ON_TPU:
            assert K.resolve_use_pallas("auto") == (False, False)
        assert K.resolve_use_pallas("auto", traced=True)[0] in (False, _ON_TPU)
        monkeypatch.setenv("METRICS_TPU_PALLAS", "never")
        assert K.resolve_use_pallas("auto") == (False, False)
        monkeypatch.setenv("METRICS_TPU_PALLAS", "force")
        assert K.resolve_use_pallas("auto")[0] is True
        with pytest.raises(ValueError):
            K.resolve_use_pallas("sometimes")

    def test_pallas_failure_falls_back_to_reference(self, monkeypatch):
        """A Pallas body that raises must land on the XLA reference with a
        fallback record, never an exception."""
        from metrics_tpu.ops.kernels import cosine_matching as cm

        def boom(*a, **kw):
            raise RuntimeError("synthetic pallas failure")

        monkeypatch.setattr(cm, "_pr_f1_pallas", boom)
        args = _random_embeddings(np.random.default_rng(15))
        want = pairwise_cosine_pr(*args, use_pallas="never")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", UserWarning)
            got = pairwise_cosine_pr(*args, use_pallas="force")
        for g, w in zip(got, want):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(w))
