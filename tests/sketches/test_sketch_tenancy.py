"""Sketch states under tenant stacking: parity, isolation, quantile reads.

Sketches are fixed-size pytrees, so TenantSet stacks them like any other
state — one vmapped executable over the tenant axis, no per-tenant
recompiles. These tests pin per-tenant isolation (one tenant's inserts never
leak into another's sketch), parity with an unstacked metric, export/import
roundtrips, and the ``read_quantiles`` read path the serve endpoint uses.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import DistinctCount, Quantile, TenantSet
from metrics_tpu.utils.exceptions import MetricsUserError


@pytest.fixture()
def rng():
    return np.random.default_rng(21)


def _feed(ts, tenant_rows):
    for tid, rows in tenant_rows.items():
        for row in rows:
            ts.apply_batch([tid], (jnp.asarray(row)[None],), auto_admit=True)


def test_stacked_quantile_parity_and_isolation(rng):
    ts = TenantSet(Quantile(q=0.5), capacity=4)
    tenant_rows = {
        "lo": rng.uniform(1.0, 10.0, (4, 32)).astype(np.float32),
        "hi": rng.uniform(100.0, 1000.0, (4, 32)).astype(np.float32),
    }
    _feed(ts, tenant_rows)
    out = ts.compute(["lo", "hi"])
    for tid, rows in tenant_rows.items():
        oracle = Quantile(q=0.5)
        for row in rows:
            oracle.update(jnp.asarray(row))
        got = float(out[tid]["Quantile"])
        assert got == pytest.approx(float(oracle.compute()), abs=1e-6), tid
    # isolation: the tenants' value ranges must not bleed into each other
    assert float(out["lo"]["Quantile"]) < 11.0 < 99.0 < float(out["hi"]["Quantile"])


def test_stacked_distinct_count_parity(rng):
    ts = TenantSet(DistinctCount(), capacity=4)
    keys = {
        "a": rng.choice(10**6, size=(2, 256), replace=False).astype(np.int32),
        "b": rng.choice(10**6, size=(2, 64), replace=False).astype(np.int32),
    }
    _feed(ts, keys)
    out = ts.compute(["a", "b"])
    for tid, rows in keys.items():
        oracle = DistinctCount()
        for row in rows:
            oracle.update(jnp.asarray(row))
        assert float(out[tid]["DistinctCount"]) == pytest.approx(
            float(oracle.compute()), abs=1e-6
        ), tid


def test_export_import_roundtrip(rng):
    ts = TenantSet(Quantile(q=0.5), capacity=4)
    data = rng.uniform(1.0, 100.0, (3, 64)).astype(np.float32)
    _feed(ts, {"src": data})
    snapshot = ts.export_tenant("src")
    ts2 = TenantSet(Quantile(q=0.5), capacity=4)
    ts2.import_tenant("dst", snapshot)
    a = float(ts.compute(["src"])["src"]["Quantile"])
    b = float(ts2.compute(["dst"])["dst"]["Quantile"])
    assert a == b


def test_read_quantiles(rng):
    ts = TenantSet(Quantile(q=0.5), capacity=4)
    data = rng.uniform(1.0, 100.0, (8, 64)).astype(np.float32)
    _feed(ts, {"t": data})
    qs = [0.1, 0.5, 0.99]
    got = ts.read_quantiles("t", qs)
    assert set(got) == {"Quantile"}
    exact = np.quantile(data.ravel(), qs, method="inverted_cdf")
    np.testing.assert_allclose(got["Quantile"], exact, rtol=0.011)
    # any quantile evaluates from the same state — not just the ctor's q
    (p25,) = ts.read_quantiles("t", [0.25])["Quantile"]
    assert p25 == pytest.approx(
        float(np.quantile(data.ravel(), 0.25, method="inverted_cdf")), rel=0.011
    )


def test_read_quantiles_rejects_bad_input(rng):
    ts = TenantSet(Quantile(q=0.5), capacity=2)
    _feed(ts, {"t": rng.uniform(1.0, 2.0, (1, 8)).astype(np.float32)})
    with pytest.raises(MetricsUserError):
        ts.read_quantiles("missing", [0.5])
    with pytest.raises(MetricsUserError):
        ts.read_quantiles("t", [1.5])
    with pytest.raises(MetricsUserError):
        ts.read_quantiles("t", [])


def test_read_quantiles_skips_sketchless_metrics(rng):
    ts = TenantSet(DistinctCount(), capacity=2)
    _feed(ts, {"t": rng.integers(0, 100, (1, 16)).astype(np.int32)})
    assert ts.read_quantiles("t", [0.5]) == {}
