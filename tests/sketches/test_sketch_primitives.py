"""Mergeable sketch primitives: error bounds, bitwise merge algebra, pytrees.

The merge contract is the load-bearing one — every component reduction is a
commutative, associative elementwise fold (sum/max/min of integer counts or
extrema), so any shard/fold order produces *bitwise* identical state. That is
what lets sketches ride the bucketed sync and incremental streaks with zero
new distributed code.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.sketches import (
    CountMinSketch,
    DyadicCountMinSketch,
    HyperLogLogSketch,
    QuantileSketch,
)
from metrics_tpu.sketches.base import SKETCH_CLASSES, is_sketch


def _bitwise_equal(a, b) -> bool:
    return all(
        np.array_equal(np.asarray(getattr(a, f)), np.asarray(getattr(b, f)))
        for f, _ in a.sketch_fields
    )


ALL_SKETCHES = [QuantileSketch, HyperLogLogSketch, CountMinSketch, DyadicCountMinSketch]


@pytest.fixture()
def rng():
    return np.random.default_rng(1234)


# --------------------------------------------------------------------------- #
# shared contracts
# --------------------------------------------------------------------------- #
@pytest.mark.parametrize("cls", ALL_SKETCHES)
def test_registered_and_marked(cls):
    assert cls.__name__ in SKETCH_CLASSES
    sk = cls()
    assert is_sketch(sk)
    assert sk.sketch_fields and all(r in ("sum", "max", "min") for _, r in sk.sketch_fields)


@pytest.mark.parametrize("cls", ALL_SKETCHES)
def test_pytree_roundtrip(cls, rng):
    sk = cls().insert(jnp.asarray(rng.integers(0, 1000, 64), jnp.int32))
    leaves, treedef = jax.tree_util.tree_flatten(sk)
    rebuilt = jax.tree_util.tree_unflatten(treedef, leaves)
    assert type(rebuilt) is cls
    assert rebuilt.config_dict() == sk.config_dict()
    assert _bitwise_equal(rebuilt, sk)


@pytest.mark.parametrize("cls", ALL_SKETCHES)
def test_config_roundtrip(cls):
    sk = cls()
    clone = type(sk).from_config(sk.config_dict())
    assert clone.config_dict() == sk.config_dict()
    # fresh components, same shapes/dtypes
    for f, _ in sk.sketch_fields:
        assert getattr(clone, f).shape == getattr(sk, f).shape
        assert getattr(clone, f).dtype == getattr(sk, f).dtype


@pytest.mark.parametrize("cls", ALL_SKETCHES)
def test_state_nbytes_fixed(cls, rng):
    sk = cls()
    before = sk.state_nbytes
    sk = sk.insert(jnp.asarray(rng.integers(0, 10**6, 4096), jnp.int32))
    assert sk.state_nbytes == before  # bounded memory: inserts never grow state


@pytest.mark.parametrize("cls", ALL_SKETCHES)
def test_merge_bitwise_order_invariance(cls, rng):
    parts = [
        cls().insert(jnp.asarray(rng.integers(0, 500, 64), jnp.int32))
        for _ in range(5)
    ]
    fwd = parts[0]
    for p in parts[1:]:
        fwd = fwd.merge(p)
    rev = parts[-1]
    for p in parts[-2::-1]:
        rev = rev.merge(p)
    # tree-shaped fold, different association
    tree = parts[0].merge(parts[1]).merge(parts[2].merge(parts[3].merge(parts[4])))
    assert _bitwise_equal(fwd, rev)
    assert _bitwise_equal(fwd, tree)


# --------------------------------------------------------------------------- #
# quantile
# --------------------------------------------------------------------------- #
def test_quantile_relative_error_bound(rng):
    data = rng.lognormal(mean=2.0, sigma=1.5, size=20000).astype(np.float32)
    sk = QuantileSketch().insert(jnp.asarray(data))
    qs = np.asarray([0.01, 0.25, 0.5, 0.75, 0.99], np.float32)
    got = np.asarray(sk.quantile(jnp.asarray(qs)))
    exact = np.quantile(data, qs, method="inverted_cdf")
    gamma = sk.error_bound()["value"]
    np.testing.assert_array_less(np.abs(got - exact) / exact, gamma + 1e-6)


def test_quantile_merge_equals_whole_stream(rng):
    data = rng.uniform(0.1, 100.0, size=512).astype(np.float32)
    whole = QuantileSketch().insert(jnp.asarray(data))
    merged = QuantileSketch().insert(jnp.asarray(data[:200])).merge(
        QuantileSketch().insert(jnp.asarray(data[200:]))
    )
    assert _bitwise_equal(whole, merged)


def test_quantile_drops_nonfinite_and_handles_empty():
    sk = QuantileSketch()
    assert np.isnan(np.asarray(sk.quantile(jnp.asarray(0.5))))
    sk = sk.insert(jnp.asarray([np.nan, np.inf, -np.inf, 5.0], jnp.float32))
    assert int(sk.count) == 1
    assert np.asarray(sk.quantile(jnp.asarray(0.5))) == pytest.approx(5.0, rel=0.011)


def test_quantile_negative_values(rng):
    data = np.concatenate([
        -rng.uniform(0.1, 50.0, 300), rng.uniform(0.1, 50.0, 300),
    ]).astype(np.float32)
    sk = QuantileSketch().insert(jnp.asarray(data))
    qs = np.asarray([0.1, 0.5, 0.9], np.float32)
    got = np.asarray(sk.quantile(jnp.asarray(qs)))
    exact = np.quantile(data, qs, method="inverted_cdf")
    np.testing.assert_allclose(got, exact, rtol=0.011, atol=1e-6)


def test_quantile_clamped_to_observed_range():
    sk = QuantileSketch().insert(jnp.asarray([3.0, 4.0, 5.0], jnp.float32))
    assert float(sk.quantile(jnp.asarray(0.0))) >= 3.0 - 1e-6
    assert float(sk.quantile(jnp.asarray(1.0))) <= 5.0 + 1e-6


# --------------------------------------------------------------------------- #
# hyperloglog
# --------------------------------------------------------------------------- #
@pytest.mark.parametrize("true_n", [100, 5000, 50000])
def test_hll_cardinality_error(true_n, rng):
    keys = rng.choice(10**7, size=true_n, replace=False).astype(np.int32)
    # duplicates must not change the estimate
    stream = np.concatenate([keys, keys[: true_n // 2]])
    sk = HyperLogLogSketch().insert(jnp.asarray(stream))
    est = float(sk.estimate())
    sigma = sk.error_bound()["value"]
    assert abs(est - true_n) / true_n < 4 * sigma


def test_hll_merge_is_union(rng):
    a_keys = np.arange(0, 3000, dtype=np.int32)
    b_keys = np.arange(1500, 4500, dtype=np.int32)  # 50% overlap
    a = HyperLogLogSketch().insert(jnp.asarray(a_keys))
    b = HyperLogLogSketch().insert(jnp.asarray(b_keys))
    union = HyperLogLogSketch().insert(jnp.asarray(np.concatenate([a_keys, b_keys])))
    assert _bitwise_equal(a.merge(b), union)


# --------------------------------------------------------------------------- #
# count-min / heavy hitters
# --------------------------------------------------------------------------- #
def test_countmin_overestimates_only(rng):
    keys = rng.integers(0, 2**15, size=8192).astype(np.int32)
    sk = CountMinSketch().insert(jnp.asarray(keys))
    uniq, true_counts = np.unique(keys, return_counts=True)
    est = np.asarray(sk.query(jnp.asarray(uniq.astype(np.int32))))
    assert np.all(est >= true_counts)  # one-sided error
    # eps * N additive bound (e/width), generous slack for the small grid
    eps = sk.error_bound()["value"]
    assert np.mean(est - true_counts) <= 3 * eps * len(keys)


def test_dyadic_heavy_hitters_finds_true_heavies(rng):
    heavy = {7: 4000, 123: 2500, 9001: 1500}
    tail = rng.integers(0, 2**16, size=2000).astype(np.int64)
    stream = np.concatenate(
        [np.full(n, k, np.int64) for k, n in heavy.items()] + [tail]
    )
    rng.shuffle(stream)
    sk = DyadicCountMinSketch().insert(jnp.asarray(stream.astype(np.int32)))
    keys, counts = sk.heavy_hitters(threshold=0.1, max_hitters=8)
    keys, counts = np.asarray(keys), np.asarray(counts)
    found = {int(k): int(c) for k, c in zip(keys, counts) if c > 0}
    for k, n in heavy.items():
        assert k in found, (k, found)
        assert found[k] >= n  # count-min never undercounts
    # sorted descending by estimated count
    valid = counts[counts > 0]
    assert np.all(valid[:-1] >= valid[1:])


def test_jit_insert_matches_eager(rng):
    data = jnp.asarray(rng.integers(0, 1000, 256), jnp.int32)
    for cls in ALL_SKETCHES:
        eager = cls().insert(data)
        jitted = jax.jit(lambda s, x: s.insert(x))(cls(), data)
        assert _bitwise_equal(eager, jitted), cls.__name__
