"""Sketch-backed metric facades: accuracy vs exact oracles, roundtrips.

Covers the new aggregation metrics (``Quantile``/``Median``,
``DistinctCount``, ``HeavyHitters``) and the ``AUROC(approx="sketch")`` twin
of a CatBuffer-backed metric — including the state_dict/checkpoint roundtrips
that the registry-driven sweep cannot reach for constructor variants.
"""
import warnings

import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import (
    AUROC,
    DistinctCount,
    HeavyHitters,
    Median,
    Quantile,
)
from metrics_tpu.checkpoint import restore_checkpoint, save_checkpoint
from metrics_tpu.utils.exceptions import MetricsUserError


@pytest.fixture()
def rng():
    return np.random.default_rng(99)


def test_quantile_vs_numpy(rng):
    data = rng.uniform(0.5, 200.0, size=(32, 16)).astype(np.float32)
    m = Quantile(q=[0.1, 0.5, 0.99])
    for row in data:
        m.update(jnp.asarray(row))
    got = np.asarray(m.compute())
    exact = np.quantile(data.ravel(), [0.1, 0.5, 0.99], method="inverted_cdf")
    np.testing.assert_allclose(got, exact, rtol=0.011)


def test_quantile_scalar_q_returns_scalar(rng):
    m = Quantile(q=0.5)
    m.update(jnp.asarray(rng.uniform(1.0, 10.0, 64), jnp.float32))
    assert np.asarray(m.compute()).shape == ()


def test_quantile_rejects_out_of_range_q():
    with pytest.raises((ValueError, MetricsUserError)):
        Quantile(q=1.5)


def test_median_is_quantile_half(rng):
    data = rng.uniform(1.0, 50.0, 128).astype(np.float32)
    med, q = Median(), Quantile(q=0.5)
    med.update(jnp.asarray(data))
    q.update(jnp.asarray(data))
    assert float(med.compute()) == float(q.compute())


def test_distinct_count(rng):
    true_n = 4000
    keys = rng.choice(10**6, size=true_n, replace=False).astype(np.int32)
    m = DistinctCount()
    m.update(jnp.asarray(keys))
    m.update(jnp.asarray(keys[:1000]))  # repeats must not inflate
    sigma = m.sketch.error_bound()["value"]
    assert abs(float(m.compute()) - true_n) / true_n < 4 * sigma


def test_heavy_hitters(rng):
    stream = np.concatenate([
        np.full(5000, 42, np.int64),
        np.full(3000, 7, np.int64),
        rng.integers(0, 2**16, size=2000),
    ])
    rng.shuffle(stream)
    m = HeavyHitters(threshold=0.1, max_hitters=4)
    m.update(jnp.asarray(stream.astype(np.int32)))
    out = m.compute()
    found = {int(k): int(c) for k, c in zip(np.asarray(out["keys"]), np.asarray(out["counts"])) if c > 0}
    assert 42 in found and 7 in found
    assert found[42] >= 5000 and found[7] >= 3000


def test_quantile_reset_and_reuse(rng):
    m = Quantile(q=0.5)
    m.update(jnp.asarray(rng.uniform(100.0, 200.0, 64), jnp.float32))
    m.reset()
    data = rng.uniform(1.0, 2.0, 64).astype(np.float32)
    m.update(jnp.asarray(data))
    exact = np.quantile(data, 0.5, method="inverted_cdf")
    assert float(m.compute()) == pytest.approx(exact, rel=0.011)


# --------------------------------------------------------------------------- #
# AUROC sketch twin
# --------------------------------------------------------------------------- #
def _binary_scores(rng, n=4000):
    target = (rng.uniform(size=n) < 0.4).astype(np.int32)
    preds = np.clip(
        rng.normal(0.35, 0.15, n) + 0.25 * target, 1e-4, 1.0
    ).astype(np.float32)
    return preds, target


def test_auroc_sketch_matches_exact(rng):
    preds, target = _binary_scores(rng)
    exact, approx = AUROC(pos_label=1), AUROC(pos_label=1, approx="sketch")
    for lo in range(0, len(preds), 500):
        exact.update(jnp.asarray(preds[lo:lo + 500]), jnp.asarray(target[lo:lo + 500]))
        approx.update(jnp.asarray(preds[lo:lo + 500]), jnp.asarray(target[lo:lo + 500]))
    assert float(approx.compute()) == pytest.approx(float(exact.compute()), abs=5e-3)


def test_auroc_sketch_state_is_fixed_size(rng):
    m = AUROC(pos_label=1, approx="sketch")
    preds, target = _binary_scores(rng, n=256)
    m.update(jnp.asarray(preds), jnp.asarray(target))
    before = m.pos_scores.state_nbytes + m.neg_scores.state_nbytes
    preds, target = _binary_scores(rng, n=4096)
    m.update(jnp.asarray(preds), jnp.asarray(target))
    assert m.pos_scores.state_nbytes + m.neg_scores.state_nbytes == before


def test_auroc_sketch_rejects_multiclass_and_max_fpr():
    with pytest.raises(MetricsUserError):
        AUROC(num_classes=3, approx="sketch")
    with pytest.raises(MetricsUserError):
        AUROC(approx="sketch", max_fpr=0.5)
    with pytest.raises(ValueError):
        AUROC(approx="nope")


def test_auroc_sketch_state_dict_roundtrip(rng):
    preds, target = _binary_scores(rng, n=512)
    m1 = AUROC(pos_label=1, approx="sketch")
    m1.update(jnp.asarray(preds), jnp.asarray(target))
    m2 = AUROC(pos_label=1, approx="sketch")
    m2.load_state_dict(m1.state_dict())
    for name in ("pos_scores", "neg_scores"):
        a, b = getattr(m1, name), getattr(m2, name)
        for f, _ in a.sketch_fields:
            np.testing.assert_array_equal(np.asarray(getattr(a, f)), np.asarray(getattr(b, f)))
    assert float(m2.compute()) == float(m1.compute())


def test_auroc_sketch_checkpoint_roundtrip(rng, tmp_path):
    preds, target = _binary_scores(rng, n=512)
    m1 = AUROC(pos_label=1, approx="sketch")
    m1.update(jnp.asarray(preds), jnp.asarray(target))
    save_checkpoint(m1, tmp_path).wait()
    m2 = AUROC(pos_label=1, approx="sketch")
    restore_checkpoint(m2, tmp_path)
    assert float(m2.compute()) == float(m1.compute())


def test_quantile_checkpoint_roundtrip(rng, tmp_path):
    m1 = Quantile(q=[0.5, 0.9])
    m1.update(jnp.asarray(rng.uniform(1.0, 100.0, 256), jnp.float32))
    save_checkpoint(m1, tmp_path).wait()
    m2 = Quantile(q=[0.5, 0.9])
    restore_checkpoint(m2, tmp_path)
    np.testing.assert_array_equal(np.asarray(m1.compute()), np.asarray(m2.compute()))


def test_declared_tolerances_feed_the_gate():
    # the PR-14 error-budget gate and PR-17 autotuner read these declarations;
    # a sketch metric must declare its error bound as the sync tolerance
    q = Quantile(q=0.5, relative_accuracy=0.02)
    assert q.sync_tolerances["sketch"] == pytest.approx(0.02)
    d = DistinctCount()
    assert d.sync_tolerances["sketch"] == pytest.approx(d.sketch.error_bound()["value"])
    a = AUROC(approx="sketch", relative_accuracy=0.015)
    assert a.sync_tolerances["pos_scores"] == pytest.approx(0.015)
    assert a.sync_tolerances["neg_scores"] == pytest.approx(0.015)
