"""Sketch states under the 8-device mesh: bitwise merge-order invariance.

The acceptance contract of the sketch subsystem: syncing a sketch state over
the mesh produces *bitwise* identical components no matter how the stream is
sharded (1/2/4/8 shards) or in what order shards fold — because every
component reduction is a commutative elementwise collective. These tests run
``sync_states`` inside ``shard_map`` over the session's 8 CPU devices and
compare raw component bytes, not tolerances.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from metrics_tpu import AUROC, DistinctCount, Quantile

WORLD = 8


@pytest.fixture()
def mesh():
    devices = jax.devices()
    if len(devices) < WORLD:
        pytest.skip("needs 8 devices")
    return Mesh(np.asarray(devices[:WORLD]), ("data",))


@pytest.fixture()
def rng():
    return np.random.default_rng(7)


def _components(sk):
    """Host copies of the sketch's components, scalars lifted to rank 1."""
    return {f: np.atleast_1d(np.asarray(getattr(sk, f))) for f, _ in sk.sketch_fields}


def _per_device_blocks(stacked, world):
    """Split a dim-0-concatenated shard_map output into per-device blocks."""
    return np.split(np.asarray(stacked), world)


@pytest.mark.mesh8
def test_quantile_mesh_sync_bitwise_vs_whole_stream(mesh, rng):
    m = Quantile(q=0.5)
    data = jnp.asarray(rng.uniform(0.5, 100.0, (WORLD, 64)), jnp.float32)

    def body(x):
        state = m.update_state(m.init_state(), jnp.ravel(x))
        synced = m.sync_states(state, "data")
        sk = synced["sketch"]
        return {f: jnp.atleast_1d(getattr(sk, f)) for f, _ in sk.sketch_fields}

    f = shard_map(body, mesh=mesh, in_specs=P("data"), out_specs=P("data"), check_rep=False)
    synced = f(data)
    whole = _components(m.update_state(m.init_state(), jnp.ravel(data))["sketch"])
    for fname, stacked in synced.items():
        # after the sync every device must hold bitwise the same merged
        # component, equal to a single-stream insert of the whole data
        for d, block in enumerate(_per_device_blocks(stacked, WORLD)):
            np.testing.assert_array_equal(block, whole[fname], err_msg=f"{fname}@dev{d}")


@pytest.mark.parametrize("shards", [1, 2, 4, 8])
def test_quantile_merge_states_invariant_across_shard_counts(shards, rng):
    m = Quantile(q=[0.25, 0.9])
    data = rng.uniform(0.5, 100.0, 256).astype(np.float32)
    whole = _components(m.update_state(m.init_state(), jnp.asarray(data))["sketch"])
    parts = [
        m.update_state(m.init_state(), jnp.asarray(chunk))
        for chunk in np.array_split(data, shards)
    ]
    folded = parts[0]
    for p in parts[1:]:
        folded = m.merge_states(folded, p)
    got = _components(folded["sketch"])
    for fname in whole:
        np.testing.assert_array_equal(got[fname], whole[fname], err_msg=f"{shards}:{fname}")


@pytest.mark.mesh8
def test_distinct_count_mesh_sync_estimate(mesh, rng):
    m = DistinctCount()
    keys = rng.choice(10**6, size=WORLD * 512, replace=False).astype(np.int32)
    data = jnp.asarray(keys).reshape(WORLD, 512)

    def body(x):
        state = m.update_state(m.init_state(), jnp.ravel(x))
        state = m.sync_states(state, "data")
        return jnp.atleast_1d(m.compute_state(state))

    f = shard_map(body, mesh=mesh, in_specs=P("data"), out_specs=P("data"), check_rep=False)
    per_dev = np.asarray(f(data))
    # all devices agree bitwise, and the estimate sees the union of all shards
    assert np.all(per_dev == per_dev[0])
    true_n = len(keys)
    sigma = m.sketch.error_bound()["value"]
    assert abs(per_dev[0] - true_n) / true_n < 4 * sigma


@pytest.mark.mesh8
def test_auroc_sketch_mesh_sync_matches_single_host(mesh, rng):
    m = AUROC(pos_label=1, approx="sketch")
    n = WORLD * 128
    target = (rng.uniform(size=n) < 0.5).astype(np.int32)
    preds = np.clip(rng.normal(0.4, 0.2, n) + 0.2 * target, 1e-4, 1.0).astype(np.float32)

    def body(p, t):
        state = m.update_state(m.init_state(), jnp.ravel(p), jnp.ravel(t))
        state = m.sync_states(state, "data")
        return jnp.atleast_1d(m.compute_state(state))

    f = shard_map(
        body, mesh=mesh,
        in_specs=(P("data"), P("data")),
        out_specs=P("data"),
        check_rep=False,
    )
    per_dev = np.asarray(
        f(jnp.asarray(preds).reshape(WORLD, -1), jnp.asarray(target).reshape(WORLD, -1))
    )
    assert np.all(per_dev == per_dev[0])
    single = AUROC(pos_label=1, approx="sketch")
    single.update(jnp.asarray(preds), jnp.asarray(target))
    assert per_dev[0] == pytest.approx(float(single.compute()), abs=1e-6)
