"""Regression option surfaces pinned directly against the reference.

sklearn/scipy are the primary oracles elsewhere; these cells close the loop
with the reference's own implementations where it makes choices sklearn
doesn't expose: spearman tie handling, cosine reduction modes, tweedie
powers, multioutput folding (reference functional/regression/*.py).
"""
import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu.functional as mtf

_rng = np.random.default_rng(44)
N, D = 96, 3
PREDS = _rng.standard_normal((N, D)).astype(np.float32)
TARGET = (0.6 * PREDS + 0.4 * _rng.standard_normal((N, D))).astype(np.float32)


def _ref():
    from tests.conftest import reference_functional

    return reference_functional()


def test_spearman_ties_vs_reference():
    torch, F = _ref()
    rng = np.random.default_rng(45)  # own rng: cell reproducible in isolation
    preds = np.round(rng.random(64) * 5).astype(np.float32)  # heavy ties
    target = np.round(rng.random(64) * 5).astype(np.float32)
    ours = float(mtf.spearman_corrcoef(jnp.asarray(preds), jnp.asarray(target)))
    want = float(F.spearman_corrcoef(torch.tensor(preds), torch.tensor(target)))
    np.testing.assert_allclose(ours, want, atol=1e-5)


@pytest.mark.parametrize("reduction", ["mean", "sum", "none"])
def test_cosine_reduction_vs_reference(reduction):
    torch, F = _ref()
    ours = mtf.cosine_similarity(jnp.asarray(PREDS), jnp.asarray(TARGET), reduction=reduction)
    want = F.cosine_similarity(torch.tensor(PREDS), torch.tensor(TARGET), reduction=reduction)
    np.testing.assert_allclose(np.asarray(ours), np.asarray(want), rtol=1e-5)


@pytest.mark.parametrize("power", [0.0, 1.0, 1.5, 2.0, 3.0, -1.0])
def test_tweedie_powers_vs_reference(power):
    torch, F = _ref()
    rng = np.random.default_rng(46)  # own rng: cell reproducible in isolation
    preds = (rng.random(64) + 0.1).astype(np.float32)
    target = (rng.random(64) + 0.1).astype(np.float32)
    ours = float(mtf.tweedie_deviance_score(jnp.asarray(preds), jnp.asarray(target), power=power))
    want = float(F.tweedie_deviance_score(torch.tensor(preds), torch.tensor(target), power=power))
    np.testing.assert_allclose(ours, want, rtol=1e-4)


@pytest.mark.parametrize("multioutput", ["raw_values", "uniform_average", "variance_weighted"])
def test_explained_variance_vs_reference(multioutput):
    torch, F = _ref()
    ours = mtf.explained_variance(jnp.asarray(PREDS), jnp.asarray(TARGET), multioutput=multioutput)
    want = F.explained_variance(torch.tensor(PREDS), torch.tensor(TARGET), multioutput=multioutput)
    np.testing.assert_allclose(np.asarray(ours), np.asarray(want), rtol=1e-4)


@pytest.mark.parametrize("adjusted", [0, 5])
@pytest.mark.parametrize("multioutput", ["raw_values", "uniform_average", "variance_weighted"])
def test_r2_vs_reference(multioutput, adjusted):
    torch, F = _ref()
    ours = mtf.r2_score(jnp.asarray(PREDS), jnp.asarray(TARGET), multioutput=multioutput, adjusted=adjusted)
    want = F.r2_score(torch.tensor(PREDS), torch.tensor(TARGET), multioutput=multioutput, adjusted=adjusted)
    np.testing.assert_allclose(np.asarray(ours), np.asarray(want), rtol=1e-4)


@pytest.mark.parametrize("squared", [True, False], ids=["mse", "rmse"])
def test_mse_squared_vs_reference(squared):
    torch, F = _ref()
    ours = float(mtf.mean_squared_error(jnp.asarray(PREDS), jnp.asarray(TARGET), squared=squared))
    want = float(F.mean_squared_error(torch.tensor(PREDS), torch.tensor(TARGET), squared=squared))
    np.testing.assert_allclose(ours, want, rtol=1e-5)
