"""Pairwise functional parity vs sklearn.

Reference parity: tests/pairwise/test_pairwise_distance.py.
"""
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics.pairwise import cosine_similarity as sk_cosine
from sklearn.metrics.pairwise import euclidean_distances as sk_euclidean
from sklearn.metrics.pairwise import linear_kernel as sk_linear
from sklearn.metrics.pairwise import manhattan_distances as sk_manhattan

from metrics_tpu.ops.pairwise import (
    pairwise_cosine_similarity,
    pairwise_euclidean_distance,
    pairwise_linear_similarity,
    pairwise_manhattan_distance,
)

_rng = np.random.default_rng(5)
X = _rng.random((10, 4)).astype(np.float32)
Y = _rng.random((7, 4)).astype(np.float32)


@pytest.mark.parametrize(
    "tm_fn,sk_fn",
    [
        (pairwise_cosine_similarity, sk_cosine),
        (pairwise_euclidean_distance, sk_euclidean),
        (pairwise_linear_similarity, sk_linear),
        (pairwise_manhattan_distance, sk_manhattan),
    ],
)
def test_pairwise_xy(tm_fn, sk_fn):
    res = tm_fn(jnp.asarray(X), jnp.asarray(Y))
    np.testing.assert_allclose(np.asarray(res), sk_fn(X, Y), atol=1e-5)


@pytest.mark.parametrize(
    "tm_fn,sk_fn",
    [
        (pairwise_cosine_similarity, sk_cosine),
        (pairwise_euclidean_distance, sk_euclidean),
    ],
)
def test_pairwise_self_zero_diagonal(tm_fn, sk_fn):
    res = np.asarray(tm_fn(jnp.asarray(X)))
    expected = sk_fn(X)
    np.fill_diagonal(expected, 0)
    np.testing.assert_allclose(res, expected, atol=1e-5)


@pytest.mark.parametrize("reduction", ["mean", "sum"])
def test_reductions(reduction):
    res = pairwise_linear_similarity(jnp.asarray(X), jnp.asarray(Y), reduction=reduction)
    mat = sk_linear(X, Y)
    expected = mat.mean(-1) if reduction == "mean" else mat.sum(-1)
    np.testing.assert_allclose(np.asarray(res), expected, atol=1e-4)


def test_bad_input():
    with pytest.raises(ValueError, match="Expected argument `x`"):
        pairwise_cosine_similarity(jnp.ones(3))
    with pytest.raises(ValueError, match="Expected argument `y`"):
        pairwise_cosine_similarity(jnp.ones((3, 2)), jnp.ones((3, 4)))


def test_zero_row_cosine_diagonal_cleared():
    """Regression: NaN diagonal (0/0) must be cleared by zero_diagonal."""
    x = np.zeros((3, 4), dtype=np.float32)
    x[1] = 1.0
    res = np.asarray(pairwise_cosine_similarity(jnp.asarray(x)))
    assert np.isfinite(np.diag(res)).all() and (np.diag(res) == 0).all()
