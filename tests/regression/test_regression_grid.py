"""bf16-precision and differentiability grid over regression functionals.

Reference parity: tests/helpers/testers.py:478-570 (fp16 + gradcheck runs per
metric); asserted here across the full regression functional surface.
"""
import numpy as np
import pytest

from metrics_tpu import ops
from tests.helpers.testers import MetricTester

_t = MetricTester()
_rng = np.random.default_rng(31)

# strictly positive values keep log/percentage metrics well-defined
PREDS = (0.2 + _rng.random((2, 16))).astype(np.float32)
TARGET = (0.2 + _rng.random((2, 16))).astype(np.float32)
PREDS_2D = (0.2 + _rng.random((2, 16, 4))).astype(np.float32)
TARGET_2D = (0.2 + _rng.random((2, 16, 4))).astype(np.float32)

CASES = [
    ("mse", lambda p, t: ops.mean_squared_error(p, t), False),
    ("mae", lambda p, t: ops.mean_absolute_error(p, t), False),
    ("msle", lambda p, t: ops.mean_squared_log_error(p, t), False),
    ("mape", lambda p, t: ops.mean_absolute_percentage_error(p, t), False),
    ("smape", lambda p, t: ops.symmetric_mean_absolute_percentage_error(p, t), False),
    ("wmape", lambda p, t: ops.weighted_mean_absolute_percentage_error(p, t), False),
    ("explained_variance", lambda p, t: ops.explained_variance(p, t), False),
    ("r2", lambda p, t: ops.r2_score(p, t), False),
    ("pearson", lambda p, t: ops.pearson_corrcoef(p, t), False),
    ("spearman", lambda p, t: ops.spearman_corrcoef(p, t.astype(p.dtype)), True),  # ranking: no grad
    ("cosine", lambda p, t: ops.cosine_similarity(p, t), False),
    ("tweedie", lambda p, t: ops.tweedie_deviance_score(p, t, power=1.5), False),
]


@pytest.mark.parametrize("name,fn,skip_grad", CASES, ids=[c[0] for c in CASES])
def test_bf16_precision(name, fn, skip_grad):
    preds, target = (PREDS_2D, TARGET_2D) if name == "cosine" else (PREDS, TARGET)
    _t.run_precision_test(preds, target, fn)


@pytest.mark.parametrize(
    "name,fn,skip_grad", [c for c in CASES if not c[2]], ids=[c[0] for c in CASES if not c[2]]
)
def test_differentiability(name, fn, skip_grad):
    preds, target = (PREDS_2D, TARGET_2D) if name == "cosine" else (PREDS, TARGET)
    _t.run_differentiability_test(preds, target, fn)
