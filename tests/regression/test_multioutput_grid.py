"""Multioutput option grid for moment-based regression metrics.

Reference analog: tests/regression/test_explained_variance.py:30-76 and
tests/regression/test_r2.py:36-92 sweep multioutput ∈ {raw_values,
uniform_average, variance_weighted} (× adjusted for R2) × ddp against the
sklearn oracles on (N, d) outputs; tests/regression/test_mean_error.py
parametrizes the error family over input views. Same cells here on the
8-device CPU mesh world merge.
"""
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import explained_variance_score, mean_squared_error as sk_mse, r2_score as sk_r2

from metrics_tpu import ExplainedVariance, MeanSquaredError, R2Score, TweedieDevianceScore
from metrics_tpu.functional import explained_variance, r2_score
from tests.helpers.testers import MetricTester

NB, BS, D = 8, 32, 3
_rng = np.random.default_rng(321)
_preds = _rng.standard_normal((NB, BS, D)).astype(np.float32)
# correlate target with preds so variance_weighted/raw_values differ meaningfully
_target = (0.7 * _preds + 0.3 * _rng.standard_normal((NB, BS, D))).astype(np.float32)

MULTIOUTPUT = ["raw_values", "uniform_average", "variance_weighted"]


@pytest.mark.parametrize("ddp", [False, True])
@pytest.mark.parametrize("multioutput", MULTIOUTPUT)
def test_explained_variance_multioutput(ddp, multioutput):
    MetricTester().run_class_metric_test(
        ddp=ddp,
        preds=_preds,
        target=_target,
        metric_class=ExplainedVariance,
        sk_metric=lambda p, t: explained_variance_score(t, p, multioutput=multioutput),
        metric_args={"multioutput": multioutput},
        check_batch=False,
    )


@pytest.mark.parametrize("ddp", [False, True])
@pytest.mark.parametrize("adjusted", [0, 5])
@pytest.mark.parametrize("multioutput", MULTIOUTPUT)
def test_r2_multioutput_adjusted(ddp, multioutput, adjusted):
    if adjusted and multioutput == "raw_values":
        pytest.skip("adjusted R2 is a scalar correction; raw_values keeps per-output values")

    def sk(p, t):
        r2 = sk_r2(t, p, multioutput=multioutput)
        if adjusted:
            n = t.shape[0]
            r2 = 1 - (1 - r2) * (n - 1) / (n - adjusted - 1)
        return r2

    MetricTester().run_class_metric_test(
        ddp=ddp,
        preds=_preds,
        target=_target,
        metric_class=R2Score,
        sk_metric=sk,
        metric_args={"num_outputs": D, "adjusted": adjusted, "multioutput": multioutput},
        check_batch=False,
    )


@pytest.mark.parametrize("multioutput", MULTIOUTPUT)
def test_functional_multioutput_parity(multioutput):
    p, t = _preds.reshape(-1, D), _target.reshape(-1, D)
    np.testing.assert_allclose(
        np.asarray(explained_variance(jnp.asarray(p), jnp.asarray(t), multioutput=multioutput)),
        explained_variance_score(t, p, multioutput=multioutput),
        atol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(r2_score(jnp.asarray(p), jnp.asarray(t), multioutput=multioutput)),
        sk_r2(t, p, multioutput=multioutput),
        atol=1e-5,
    )


@pytest.mark.parametrize("ddp", [False, True])
@pytest.mark.parametrize("squared", [True, False])
def test_mse_num_outputs(ddp, squared):
    """Per-output MSE/RMSE state ((d,) sums) through the world merge."""

    def sk(p, t):
        val = sk_mse(t.reshape(-1, D), p.reshape(-1, D), multioutput="raw_values")
        return val if squared else np.sqrt(val)

    MetricTester().run_class_metric_test(
        ddp=ddp,
        preds=_preds,
        target=_target,
        metric_class=MeanSquaredError,
        sk_metric=sk,
        metric_args={"squared": squared, "num_outputs": D},
        check_batch=False,
    )


def test_r2_raw_values_matches_per_output_scalars():
    """raw_values == stacking d independent single-output R2 scores."""
    p, t = _preds.reshape(-1, D), _target.reshape(-1, D)
    raw = np.asarray(r2_score(jnp.asarray(p), jnp.asarray(t), multioutput="raw_values"))
    per = [float(r2_score(jnp.asarray(p[:, j]), jnp.asarray(t[:, j]))) for j in range(D)]
    np.testing.assert_allclose(raw, per, atol=1e-5)


@pytest.mark.parametrize("power", [0.25, 0.5, 0.75])
def test_tweedie_invalid_power_raises(power):
    """Deviance is undefined for 0 < power < 1 (reference raises there; negative
    powers are legal extreme-stable cases)."""
    with pytest.raises(ValueError):
        m = TweedieDevianceScore(power=power)
        m.update(jnp.ones(4), jnp.ones(4))


def test_tweedie_negative_power_parity():
    from sklearn.metrics import mean_tweedie_deviance

    p = _rng.random(64).astype(np.float64) + 0.1  # strictly positive preds
    t = _rng.standard_normal(64).astype(np.float64)
    m = TweedieDevianceScore(power=-1.0)
    m.update(jnp.asarray(p), jnp.asarray(t))
    np.testing.assert_allclose(float(m.compute()), mean_tweedie_deviance(t, p, power=-1.0), rtol=1e-4)
