"""Regression metric parity vs sklearn/scipy.

Reference parity: tests/regression/* (compacted grid).
"""
import jax.numpy as jnp
import numpy as np
import pytest
from scipy.stats import pearsonr, spearmanr
from sklearn.metrics import (
    explained_variance_score,
    mean_absolute_error as sk_mae,
    mean_absolute_percentage_error as sk_mape,
    mean_squared_error as sk_mse,
    mean_squared_log_error as sk_msle,
    mean_tweedie_deviance,
    r2_score as sk_r2,
)

from metrics_tpu.ops.regression import (
    cosine_similarity,
    explained_variance,
    mean_absolute_error,
    mean_absolute_percentage_error,
    mean_squared_error,
    mean_squared_log_error,
    pearson_corrcoef,
    r2_score,
    spearman_corrcoef,
    symmetric_mean_absolute_percentage_error,
    tweedie_deviance_score,
    weighted_mean_absolute_percentage_error,
)
from metrics_tpu.regression import (
    CosineSimilarity,
    ExplainedVariance,
    MeanAbsoluteError,
    MeanSquaredError,
    PearsonCorrCoef,
    R2Score,
    SpearmanCorrCoef,
    TweedieDevianceScore,
)
from tests.helpers.testers import MetricTester

_rng = np.random.default_rng(123)
NB, BS = 8, 32
_preds = _rng.random((NB, BS)).astype(np.float32) + 0.1
_target = _rng.random((NB, BS)).astype(np.float32) + 0.1
_preds_2d = _rng.random((NB, BS, 3)).astype(np.float32)
_target_2d = _rng.random((NB, BS, 3)).astype(np.float32)


@pytest.mark.parametrize(
    "tm_fn,sk_fn",
    [
        (mean_squared_error, sk_mse),
        (mean_absolute_error, sk_mae),
        (mean_squared_log_error, sk_msle),
        (mean_absolute_percentage_error, sk_mape),
        (r2_score, sk_r2),
        (explained_variance, explained_variance_score),
    ],
)
def test_functional_parity(tm_fn, sk_fn):
    res = tm_fn(jnp.asarray(_preds[0]), jnp.asarray(_target[0]))
    sk = sk_fn(_target[0], _preds[0])
    np.testing.assert_allclose(np.asarray(res), sk, atol=1e-5)


def test_rmse():
    res = mean_squared_error(jnp.asarray(_preds[0]), jnp.asarray(_target[0]), squared=False)
    np.testing.assert_allclose(np.asarray(res), np.sqrt(sk_mse(_target[0], _preds[0])), atol=1e-6)


def test_pearson_functional():
    res = pearson_corrcoef(jnp.asarray(_preds[0]), jnp.asarray(_target[0]))
    np.testing.assert_allclose(np.asarray(res), pearsonr(_preds[0], _target[0])[0], atol=1e-5)


def test_spearman_with_ties():
    p = np.round(_preds[0], 1)  # force ties
    t = np.round(_target[0], 1)
    res = spearman_corrcoef(jnp.asarray(p), jnp.asarray(t))
    np.testing.assert_allclose(np.asarray(res), spearmanr(p, t)[0], atol=1e-5)


def test_smape():
    res = symmetric_mean_absolute_percentage_error(jnp.asarray(_preds[0]), jnp.asarray(_target[0]))
    expected = np.mean(2 * np.abs(_preds[0] - _target[0]) / (np.abs(_preds[0]) + np.abs(_target[0])))
    np.testing.assert_allclose(np.asarray(res), expected, atol=1e-5)


def test_wmape():
    res = weighted_mean_absolute_percentage_error(jnp.asarray(_preds[0]), jnp.asarray(_target[0]))
    expected = np.sum(np.abs(_preds[0] - _target[0])) / np.sum(np.abs(_target[0]))
    np.testing.assert_allclose(np.asarray(res), expected, atol=1e-5)


@pytest.mark.parametrize("power", [0, 1, 2, 3, 1.5])
def test_tweedie(power):
    res = tweedie_deviance_score(jnp.asarray(_preds[0]), jnp.asarray(_target[0]), power=power)
    sk = mean_tweedie_deviance(_target[0], _preds[0], power=power)
    np.testing.assert_allclose(np.asarray(res), sk, atol=1e-5, rtol=1e-4)


@pytest.mark.parametrize("reduction", ["sum", "mean", "none"])
def test_cosine_similarity(reduction):
    p, t = _preds_2d[0], _target_2d[0]
    res = cosine_similarity(jnp.asarray(p), jnp.asarray(t), reduction=reduction)
    sims = np.sum(p * t, -1) / (np.linalg.norm(p, axis=-1) * np.linalg.norm(t, axis=-1))
    expected = {"sum": sims.sum(), "mean": sims.mean(), "none": sims}[reduction]
    np.testing.assert_allclose(np.asarray(res), expected, atol=1e-5)


# --------------------------------------------------------------------------- #
# module classes incl. ddp
# --------------------------------------------------------------------------- #
@pytest.mark.parametrize("ddp", [False, True])
@pytest.mark.parametrize(
    "metric_class,sk_fn",
    [
        (MeanSquaredError, sk_mse),
        (MeanAbsoluteError, sk_mae),
        (R2Score, sk_r2),
        (ExplainedVariance, explained_variance_score),
    ],
)
def test_class_parity(ddp, metric_class, sk_fn):
    MetricTester().run_class_metric_test(
        ddp=ddp,
        preds=_preds,
        target=_target,
        metric_class=metric_class,
        sk_metric=lambda p, t: sk_fn(t, p),
        metric_args={},
        check_batch=metric_class not in (R2Score, ExplainedVariance),
    )


@pytest.mark.parametrize("ddp", [False, True])
def test_pearson_class(ddp):
    MetricTester().run_class_metric_test(
        ddp=ddp,
        preds=_preds,
        target=_target,
        metric_class=PearsonCorrCoef,
        sk_metric=lambda p, t: pearsonr(p.reshape(-1), t.reshape(-1))[0],
        metric_args={},
        check_batch=False,
    )


@pytest.mark.parametrize("ddp", [False, True])
def test_spearman_class(ddp):
    MetricTester().run_class_metric_test(
        ddp=ddp,
        preds=_preds,
        target=_target,
        metric_class=SpearmanCorrCoef,
        sk_metric=lambda p, t: spearmanr(p.reshape(-1), t.reshape(-1))[0],
        metric_args={},
        check_batch=False,
    )


def test_tweedie_class_accumulates():
    m = TweedieDevianceScore(power=1.5)
    for i in range(4):
        m.update(jnp.asarray(_preds[i]), jnp.asarray(_target[i]))
    sk = mean_tweedie_deviance(_target[:4].reshape(-1), _preds[:4].reshape(-1), power=1.5)
    np.testing.assert_allclose(np.asarray(m.compute()), sk, atol=1e-5, rtol=1e-4)


def test_r2_adjusted():
    res = r2_score(jnp.asarray(_preds[0]), jnp.asarray(_target[0]), adjusted=3)
    n = BS
    base = sk_r2(_target[0], _preds[0])
    expected = 1 - (1 - base) * (n - 1) / (n - 3 - 1)
    np.testing.assert_allclose(np.asarray(res), expected, atol=1e-5)


def test_grad_flows():
    MetricTester().run_differentiability_test(_preds, _target, mean_squared_error)


def test_r2_adjusted_under_jit():
    """Regression: adjusted R2 must compile (traced n_obs)."""
    import jax

    m = R2Score(adjusted=3)
    f = jax.jit(lambda s, p, t: m.compute_state(m.update_state(s, p, t)))
    res = f(m.init_state(), jnp.asarray(_preds[0]), jnp.asarray(_target[0]))
    n = BS
    expected = 1 - (1 - sk_r2(_target[0], _preds[0])) * (n - 1) / (n - 3 - 1)
    np.testing.assert_allclose(np.asarray(res), expected, atol=1e-5)


@pytest.mark.parametrize("ddp", [False, True])
def test_cosine_similarity_class(ddp):
    MetricTester().run_class_metric_test(
        ddp=ddp,
        preds=_preds_2d,
        target=_target_2d,
        metric_class=CosineSimilarity,
        sk_metric=lambda p, t: np.sum(np.sum(p * t, -1) / (np.linalg.norm(p, axis=-1) * np.linalg.norm(t, axis=-1))),
        metric_args={"reduction": "sum"},
    )
