"""Real pretrained-checkpoint parity harness (checkpoint-dir gated).

The structural converter differentials in ``tests/image/test_generative.py``
prove key-mapping + architecture on *randomized* torch nets. This module is
the missing value-parity leg: point ``METRICS_TPU_WEIGHTS_DIR`` at a local
directory holding the community checkpoints and every test below runs the
real weights through converter -> tap-for-tap torch differential -> full
metric value parity on fixed fixtures. Without the env var the module skips
cleanly, so it is runnable today and green the day weights are available
(this environment has no network, so the weights cannot be fetched here).

Expected directory layout (any subset; each file gates only its own tests):

    $METRICS_TPU_WEIGHTS_DIR/
      pt_inception-2015-12-05*.pth     torch-fidelity FID InceptionV3
                                       (reference download site:
                                       torchmetrics/image/fid.py:27-46)
      alexnet*.pth                     torchvision AlexNet (LPIPS trunk)
      vgg16*.pth                       torchvision VGG16 (LPIPS trunk)
      lpips_alex*.pth / alex.pth       lpips lin heads, alex
                                       (torchmetrics/image/lpip.py:34-45)
      lpips_vgg*.pth / vgg.pth         lpips lin heads, vgg
      bert/ (or any dir w/ config.json) HF encoder checkpoint for BERTScore
                                       (torchmetrics/functional/text/bert.py:249-326)

Run:  METRICS_TPU_WEIGHTS_DIR=/path/to/ckpts python -m pytest tests/weights -v
"""
from __future__ import annotations

import functools
import glob
import os

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

WEIGHTS_DIR = os.environ.get("METRICS_TPU_WEIGHTS_DIR", "")

pytestmark = pytest.mark.skipif(
    not WEIGHTS_DIR or not os.path.isdir(WEIGHTS_DIR),
    reason="METRICS_TPU_WEIGHTS_DIR not set to an existing checkpoint directory",
)

_rng = np.random.default_rng(20260731)


def _find(*patterns: str) -> str | None:
    for pat in patterns:
        hits = sorted(glob.glob(os.path.join(WEIGHTS_DIR, pat)))
        if hits:
            return hits[0]
    return None


def _torch_load(path: str):
    torch = pytest.importorskip("torch")
    sd = torch.load(path, map_location="cpu")
    if isinstance(sd, dict) and "state_dict" in sd:
        sd = sd["state_dict"]
    return {k: v for k, v in sd.items()}


def _require(path: str | None, what: str) -> str:
    if path is None:
        pytest.skip(f"{what} checkpoint not present in METRICS_TPU_WEIGHTS_DIR")
    return path


# --------------------------------------------------------------------------- #
# FID InceptionV3 (pt_inception-2015-12-05)
# --------------------------------------------------------------------------- #
@functools.lru_cache(maxsize=1)  # both FID tests share one checkpoint load
def _real_inception():
    torch = pytest.importorskip("torch")
    path = _require(_find("pt_inception*.pth", "*inception*2015*.pth"), "FID Inception")
    sd = _torch_load(path)
    from tests.helpers.torch_nets import TorchFIDInception

    net = TorchFIDInception()
    # the community checkpoint stores fc as 1x1 conv weights in some exports;
    # let strict loading report any mismatch precisely rather than masking it
    net.load_state_dict({k: torch.as_tensor(np.asarray(v)) for k, v in sd.items()})
    net.eval()

    from metrics_tpu.nets.inception import load_inception_torch_state_dict

    taps = ("64", "192", "768", "2048", "logits_unbiased")
    variables = load_inception_torch_state_dict(
        {k: np.asarray(v) for k, v in sd.items()}, features_list=taps
    )
    return net, variables, taps


def test_inception_real_weight_tap_parity():
    """Real FID weights: flax taps must match the torch oracle tap-for-tap."""
    torch = pytest.importorskip("torch")
    net, variables, taps = _real_inception()
    imgs = _rng.integers(0, 255, size=(2, 3, 299, 299)).astype(np.uint8)
    with torch.no_grad():
        want = net(torch.as_tensor(imgs))

    from metrics_tpu.nets.inception import InceptionV3, _resize_bilinear_tf1

    module = InceptionV3(features_list=taps)
    x = jnp.transpose(jnp.asarray(imgs, jnp.float32), (0, 2, 3, 1))
    x = _resize_bilinear_tf1(x, 299, 299)
    x = (x - 128.0) / 128.0
    got = module.apply(variables, x)
    for tap in taps:
        w = want[tap].numpy()
        scale = max(1e-6, float(np.abs(w).max()))
        np.testing.assert_allclose(
            np.asarray(got[tap]), w, rtol=2e-3, atol=2e-3 * scale, err_msg=f"tap {tap}"
        )


def test_fid_real_weight_value_parity():
    """Published-weight FID: same images through both pipelines -> same value."""
    torch = pytest.importorskip("torch")
    net, variables, _ = _real_inception()
    real = _rng.integers(0, 255, size=(24, 3, 96, 96)).astype(np.uint8)
    fake = np.clip(real + _rng.integers(-40, 40, size=real.shape), 0, 255).astype(np.uint8)

    from metrics_tpu.image import FrechetInceptionDistance
    from metrics_tpu.nets.inception import InceptionV3FeatureExtractor

    ext = InceptionV3FeatureExtractor("2048", variables=variables)
    fid = FrechetInceptionDistance(feature=ext)
    for i in range(0, 24, 12):
        fid.update(jnp.asarray(real[i : i + 12]), real=True)
        fid.update(jnp.asarray(fake[i : i + 12]), real=False)
    got = float(fid.compute())

    with torch.no_grad():
        rf = net(torch.as_tensor(real))["2048"].numpy().astype(np.float64)
        ff = net(torch.as_tensor(fake))["2048"].numpy().astype(np.float64)
    from tests.image.test_generative import _np_fid

    want = _np_fid(rf.mean(0), np.cov(rf, rowvar=False), ff.mean(0), np.cov(ff, rowvar=False))
    assert abs(got - want) / max(1.0, abs(want)) < 2e-2, (got, want)


# --------------------------------------------------------------------------- #
# LPIPS (torchvision trunk + lpips lin heads)
# --------------------------------------------------------------------------- #
def _lpips_state_dicts(net_type: str):
    trunk_path = _require(
        _find(f"{'alexnet' if net_type == 'alex' else 'vgg16'}*.pth"),
        f"torchvision {net_type} trunk",
    )
    lin_path = _require(
        _find(f"lpips_{net_type}*.pth", f"{net_type}.pth"), f"lpips {net_type} lin"
    )
    trunk = {k: np.asarray(v) for k, v in _torch_load(trunk_path).items() if k.startswith("features.")}
    lin = {k: np.asarray(v) for k, v in _torch_load(lin_path).items() if ".model." in k or k.startswith("lin")}
    return trunk, lin


@pytest.mark.parametrize("net_type", ["alex", "vgg"])
def test_lpips_real_weight_value_parity(net_type):
    """Real trunk+lin weights: flax LPIPS == torch oracle forward, and the
    LPIPS metric on fixed image pairs matches the torch pipeline value."""
    torch = pytest.importorskip("torch")
    trunk, lin = _lpips_state_dicts(net_type)

    from metrics_tpu.nets.lpips import LPIPSNet, load_lpips_torch_state_dict
    from tests.helpers.torch_nets import torch_lpips_forward

    variables = load_lpips_torch_state_dict(trunk, lin, net_type)
    a = _rng.uniform(-1, 1, size=(4, 3, 96, 96)).astype(np.float32)
    b = _rng.uniform(-1, 1, size=(4, 3, 96, 96)).astype(np.float32)
    want = torch_lpips_forward(
        {k: torch.as_tensor(v) for k, v in trunk.items()},
        {k: torch.as_tensor(v) for k, v in lin.items()},
        net_type,
        torch.as_tensor(a),
        torch.as_tensor(b),
    ).numpy()
    scorer = LPIPSNet(net_type=net_type, variables=variables)
    got = np.asarray(scorer(jnp.asarray(a), jnp.asarray(b)))
    np.testing.assert_allclose(got.reshape(-1), want.reshape(-1), rtol=2e-3, atol=2e-4)

    from metrics_tpu.image import LearnedPerceptualImagePatchSimilarity

    metric = LearnedPerceptualImagePatchSimilarity(net=scorer)
    metric.update(jnp.asarray(a), jnp.asarray(b))
    assert abs(float(metric.compute()) - float(want.mean())) < 5e-4


# --------------------------------------------------------------------------- #
# BERTScore (HF checkpoint dir)
# --------------------------------------------------------------------------- #
def _bert_dir() -> str:
    for cand in sorted(glob.glob(os.path.join(WEIGHTS_DIR, "*"))):
        if os.path.isdir(cand) and os.path.exists(os.path.join(cand, "config.json")):
            return cand
    pytest.skip("no HF checkpoint dir (config.json) in METRICS_TPU_WEIGHTS_DIR")


def test_bert_score_real_checkpoint_flax_vs_torch():
    """Same HF checkpoint through FlaxAutoModel (our default path) and torch
    AutoModel (via user_forward_fn) must yield the same BERTScore."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    path = _bert_dir()

    from metrics_tpu.functional import bert_score

    preds = ["the cat sat on the mat", "a quick brown fox"]
    target = ["a cat sat on a mat", "the slow brown fox jumped"]

    flax_out = bert_score(
        preds, target, model_name_or_path=path, num_layers=2, batch_size=2, max_length=32
    )

    tok = transformers.AutoTokenizer.from_pretrained(path)
    tmodel = transformers.AutoModel.from_pretrained(path, output_hidden_states=True)
    tmodel.eval()

    def torch_forward(_model, batch):
        with torch.no_grad():
            out = tmodel(
                input_ids=torch.as_tensor(np.asarray(batch["input_ids"])),
                attention_mask=torch.as_tensor(np.asarray(batch["attention_mask"])),
            )
        return np.asarray(out.hidden_states[2])

    torch_out = bert_score(
        preds,
        target,
        model=object(),
        user_tokenizer=tok,
        user_forward_fn=torch_forward,
        batch_size=2,
        max_length=32,
    )
    for key in ("precision", "recall", "f1"):
        np.testing.assert_allclose(
            np.asarray(flax_out[key]), np.asarray(torch_out[key]), rtol=1e-3, atol=1e-3,
            err_msg=key,
        )
