"""Prove the checkpoint-dir-gated parity harness is runnable end to end.

Real pretrained weights cannot exist in this offline environment, so this
selftest saves *randomized* checkpoints in the exact community file formats
(``pt_inception-2015-12-05*.pth`` key layout, torchvision ``features.N``
trunks, lpips ``lin<k>.model.1.weight`` heads, an HF ``config.json`` dir) and
runs the gated module against them in a subprocess. Every loader, converter,
torch differential, and metric value comparison executes; only the *values*
differ from the published weights. The day a real checkpoint dir exists,
``METRICS_TPU_WEIGHTS_DIR=<dir> pytest tests/weights`` is already known to
work.
"""
from __future__ import annotations

import os
import subprocess
import sys

import pytest

from tests.weights.conftest import make_synthetic_weights_dir


def test_gated_harness_runs_on_synthetic_checkpoints(tmp_path_factory):
    torch = pytest.importorskip("torch")
    weights_dir = str(tmp_path_factory.mktemp("synthetic_weights"))
    make_synthetic_weights_dir(weights_dir)

    env = dict(os.environ)
    env["METRICS_TPU_WEIGHTS_DIR"] = weights_dir
    env.setdefault("JAX_PLATFORMS", "cpu")
    out = subprocess.run(
        [
            sys.executable,
            "-m",
            "pytest",
            os.path.join(os.path.dirname(__file__), "test_real_weight_parity.py"),
            "-q",
            "-p",
            "no:cacheprovider",
        ],
        env=env,
        capture_output=True,
        text=True,
        timeout=1650,
        cwd=os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    )
    tail = (out.stdout or "")[-4000:] + (out.stderr or "")[-2000:]
    assert out.returncode == 0, tail
    assert "failed" not in out.stdout, tail
    # every gated test must actually RUN (not skip) against the synthetic dir;
    # the BERTScore leg needs the optional transformers dependency
    try:
        import transformers  # noqa: F401

        expected = "5 passed"
    except ImportError:
        expected = "4 passed, 1 skipped"
    assert expected in out.stdout, tail
