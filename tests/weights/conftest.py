"""Helpers for the real-weight parity harness tests."""
from __future__ import annotations

import os


def make_synthetic_weights_dir(path: str) -> None:
    """Populate ``path`` with randomized checkpoints saved in the exact file
    formats / key layouts of the community weights the gated harness expects
    (pt_inception .pth, torchvision trunk .pth, lpips lin .pth, HF dir).

    Values are random — the point is that every loader, converter, and
    differential in ``test_real_weight_parity.py`` executes end to end, so the
    harness is proven runnable before real weights ever arrive.
    """
    import torch

    from metrics_tpu.nets.lpips import NET_CHANNELS
    from tests.helpers.torch_nets import (
        TorchFIDInception,
        make_lpips_backbone_state_dict,
        make_lpips_lin_state_dict,
        randomize_inception_,
    )

    os.makedirs(path, exist_ok=True)
    net = TorchFIDInception()
    randomize_inception_(net, seed=11)
    torch.save(net.state_dict(), os.path.join(path, "pt_inception-2015-12-05-synthetic.pth"))
    torch.save(make_lpips_backbone_state_dict("alex", seed=12), os.path.join(path, "alexnet-synthetic.pth"))
    torch.save(
        make_lpips_lin_state_dict(NET_CHANNELS["alex"], seed=13),
        os.path.join(path, "lpips_alex_synthetic.pth"),
    )
    torch.save(make_lpips_backbone_state_dict("vgg", seed=14), os.path.join(path, "vgg16-synthetic.pth"))
    torch.save(
        make_lpips_lin_state_dict(NET_CHANNELS["vgg"], seed=15),
        os.path.join(path, "lpips_vgg_synthetic.pth"),
    )

    try:
        from transformers import BertConfig, BertModel, BertTokenizer
    except ImportError:
        return
    cfg = BertConfig(
        vocab_size=64,
        hidden_size=32,
        num_hidden_layers=3,
        num_attention_heads=2,
        intermediate_size=64,
        max_position_embeddings=64,
    )
    bert_dir = os.path.join(path, "bert")
    BertModel(cfg).save_pretrained(bert_dir)
    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"]
    vocab += ["the", "cat", "sat", "on", "mat", "a", "quick", "brown", "fox", "slow", "jumped"]
    vocab += [f"tok{i}" for i in range(64 - len(vocab))]
    with open(os.path.join(bert_dir, "vocab.txt"), "w") as fh:
        fh.write("\n".join(vocab))
    BertTokenizer(os.path.join(bert_dir, "vocab.txt")).save_pretrained(bert_dir)
