"""The tuner's hard safety floor under fault injection (chaos sweep).

The strongest property the self-tuning controller offers: no matter what
chaos does to the sync path — injected trace-time latency, aborted bucket
builds, measured-error spikes — every transport the tuner ever *selects* is
one the trace-time error-budget gate admits. Chaos can slow convergence and
poison rungs; it can never push a bucket onto a transport the gate would
refuse, and after error spikes the bucket demotes rung by rung back to
``exact`` and stays there (poisoned rungs never return).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

import metrics_tpu
from metrics_tpu import autotune as at
from metrics_tpu.autotune import bucket_key
from metrics_tpu.autotune import controller as at_controller
from metrics_tpu.parallel import sync as sync_mod
from metrics_tpu.resilience import chaos
from metrics_tpu.resilience.chaos import ChaosError, FaultSpec

WORLD = 8

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _reset():
    metrics_tpu.set_autotune(False)
    sync_mod.set_sync_transport(None)
    sync_mod.set_sync_cadence(None)
    yield
    chaos.uninstall()
    metrics_tpu.set_autotune(None)
    sync_mod.set_sync_transport(None)
    sync_mod.set_sync_cadence(None)


@pytest.fixture()
def mesh():
    devices = jax.devices()
    if len(devices) < WORLD:
        pytest.skip("needs 8 devices")
    return Mesh(np.asarray(devices[:WORLD]), ("data",))


_STATE = {
    "big": jnp.linspace(0.1, 40.0, 8192, dtype=jnp.float32),
    "counts": (jnp.arange(1000, dtype=jnp.int32) % 7),
    "mx": jnp.asarray([7.0, 1.0], jnp.float32),
}
_REDS = {"big": "sum", "counts": "sum", "mx": "max"}


def _per_device(state):
    return jax.tree_util.tree_map(
        lambda a: jnp.stack([a * (i + 1) for i in range(WORLD)]), state
    )


def _make_fn(mesh, reds, transports=None):
    def body(s):
        local = jax.tree_util.tree_map(lambda x: x[0], s)
        out = sync_mod.sync_state(
            local, reds, "data", bucketed=True, transports=transports
        )
        return jax.tree_util.tree_map(lambda x: jnp.expand_dims(x, 0), out)

    return jax.jit(
        shard_map(body, mesh=mesh, in_specs=P("data"), out_specs=P("data"),
                  check_rep=False)
    )


def _chaos_drive(mesh, state, reds, steps=40):
    """Tuned driver that survives chaos: an aborted trace is dropped and
    re-jitted on the next step (exactly what a resilient engine driver does).
    Returns (last good output, aborted-trace count)."""
    per_dev = _per_device(state)
    epoch = at.decision_epoch()
    fn = _make_fn(mesh, reds)
    aborted = 0
    out = None
    for _ in range(steps):
        if at.decision_epoch() != epoch:
            epoch = at.decision_epoch()
            fn = _make_fn(mesh, reds)
        try:
            out = fn(per_dev)
        except ChaosError:
            aborted += 1
            fn = _make_fn(mesh, reds)
    return out, aborted


def _exact_reference(mesh, state, reds):
    fn = _make_fn(mesh, reds, transports={n: "exact" for n in state})
    out = fn(_per_device(state))
    return jax.tree_util.tree_map(lambda x: np.asarray(x[0]), out)


def _assert_decisions_gate_admissible(ctl):
    """Re-run the runtime gate on every decision the tuner ever made: each
    selected transport must be admitted at the bucket's own parameters."""
    for event in ctl.decisions:
        to = event["to"]
        if to == "exact":
            continue
        tuner = ctl.buckets[event["bucket"]]
        final, refusal = sync_mod._gate_transport(
            to,
            None if tuner.kind == "reshard" else tuner.red,
            tuner.dtype,
            tuner.nelems,
            tuner.world,
            tuner.tolerance_for(to),
            kind=tuner.kind,
            error_scale=tuner.max_error_scale,
        )
        assert final == to and refusal is None, (
            f"tuner selected gate-refused transport {to!r} for "
            f"{event['bucket']}: {refusal}"
        )


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_chaos_never_pushes_past_the_gate(mesh, seed):
    """Latency + aborted-build faults at the sync seams: the tuner still
    converges to the gate-admissible optimum and never selects a refused
    transport, at any seed."""
    metrics_tpu.set_autotune(True)
    specs = [
        FaultSpec("sync/*", kind="latency", probability=0.4, latency_s=0.002),
        FaultSpec("sync/bucket_build", kind="error", probability=0.3, times=4),
    ]
    with chaos.plan(specs, seed=seed) as plan:
        _chaos_drive(mesh, _STATE, _REDS, steps=40)
        assert plan.fired("sync/bucket_build") > 0  # chaos actually hit
    ctl = at_controller.get_controller()
    _assert_decisions_gate_admissible(ctl)
    for key, tuner in ctl.buckets.items():
        assert tuner.phase == "committed", key
    # chaos slowed the walk but the destination is unchanged
    assert ctl.buckets[bucket_key("sum", np.dtype("float32"))].committed == "int8"
    assert ctl.buckets[bucket_key("max", np.dtype("float32"))].committed == "exact"
    # a post-chaos trace syncs within tolerance of the exact reference
    out = np.asarray(_make_fn(mesh, _REDS)(_per_device(_STATE))["big"][0])
    want = _exact_reference(mesh, _STATE, _REDS)["big"]
    tol = ctl.buckets[bucket_key("sum", np.dtype("float32"))].tolerance_for("int8")
    assert float(np.max(np.abs(out - want)) / np.max(np.abs(want))) <= tol


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_error_spikes_demote_to_exact_and_stay(mesh, seed):
    """Measured-error spikes (the runtime feedback channel) poison the
    current rung immediately; repeated spikes walk the bucket back to
    ``exact``, poisoned rungs never return, and the demoted integer bucket
    syncs bitwise-identical to untuned. The (deterministic) demotion path is
    swept under three chaos seeds to interleave faults with the spikes."""
    metrics_tpu.set_autotune(True)
    with chaos.plan(
        [FaultSpec("sync/bucket_build", kind="error", probability=0.25, times=3)],
        seed=seed,
    ):
        _chaos_drive(mesh, _STATE, _REDS, steps=40)
    ctl = at_controller.get_controller()
    f32, i32 = np.dtype("float32"), np.dtype("int32")
    lossless = ("exact", "sparse_count")  # both bitwise by construction
    for dtype in (f32, i32):
        tuner = ctl.buckets[bucket_key("sum", dtype)]
        assert tuner.phase == "committed" and tuner.committed != "exact"
        # spike until the bucket has demoted off every lossy rung (the i32
        # bucket may land on sparse_count — lossless, so equally safe)
        for _ in range(len(at.LADDER)):
            if tuner.current in lossless:
                break
            ctl.observe_error("sum", dtype, measured=10.0 * tuner.tolerance_for(
                tuner.current))
        assert tuner.current in lossless
        assert tuner.poisoned  # the spiked rungs are banned, not just avoided
    demotions = [d for d in ctl.decisions if d["reason"].startswith("poisoned:")]
    assert any(d["reason"] == "poisoned:error_spike" for d in demotions)
    _assert_decisions_gate_admissible(ctl)

    # poisoned rungs never reappear: further observations (well past the
    # dwell floor) leave the decision log untouched
    n_decisions = len(ctl.decisions)
    for _ in range(3 * ctl.config.min_dwell):
        for dtype in (f32, i32):
            ctl.observe_bucket(
                "sum", dtype, requested="exact", transport="exact",
                nelems=8192 if dtype is f32 else 1000, world=WORLD,
            )
    assert len(ctl.decisions) == n_decisions

    # fully demoted, the tuned sync is bitwise the untuned sync
    out, _ = _chaos_drive(mesh, _STATE, _REDS, steps=2)
    want = _exact_reference(mesh, _STATE, _REDS)
    for name in _STATE:
        np.testing.assert_array_equal(np.asarray(out[name][0]), want[name])
