"""Self-tuning sync wired into the runtime (``sync.py`` × ``autotune``).

The contract on the 8-device CPU mesh: with ``set_autotune(True)`` a driver
that re-jits when the decision epoch moves converges within the exploration
budget (one trace per ladder rung per bucket), the converged transports are
the cheapest gate-admissible rungs, realized error stays within the budget,
the epoch then stops moving (zero retraces after warmup), per-state
declarations stay invisible to the tuner, zero-tolerance buckets stay
bitwise, cadence precedence is switch > env > tuner, and tenancy-stacked
buckets tune through the same (reduction, dtype) keys independent of N.
"""
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

import metrics_tpu
from metrics_tpu import autotune as at
from metrics_tpu.autotune import PolicyConfig, bucket_key
from metrics_tpu.autotune import controller as at_controller
from metrics_tpu.parallel import sync as sync_mod

WORLD = 8


@pytest.fixture(autouse=True)
def _reset():
    metrics_tpu.set_autotune(False)
    sync_mod.set_sync_transport(None)
    sync_mod.set_sync_cadence(None)
    yield
    metrics_tpu.set_autotune(None)
    sync_mod.set_sync_transport(None)
    sync_mod.set_sync_cadence(None)


@pytest.fixture()
def mesh():
    devices = jax.devices()
    if len(devices) < WORLD:
        pytest.skip("needs 8 devices")
    return Mesh(np.asarray(devices[:WORLD]), ("data",))


_STATE = {
    "big": jnp.linspace(0.1, 40.0, 8192, dtype=jnp.float32),
    "counts": (jnp.arange(1000, dtype=jnp.int32) % 7),
    "mx": jnp.asarray([7.0, 1.0], jnp.float32),
}
_REDS = {"big": "sum", "counts": "sum", "mx": "max"}


def _per_device(state):
    return jax.tree_util.tree_map(
        lambda a: jnp.stack([a * (i + 1) for i in range(WORLD)]), state
    )


def _make_fn(mesh, reds, transports=None, tolerances=None):
    def body(s):
        local = jax.tree_util.tree_map(lambda x: x[0], s)
        out = sync_mod.sync_state(
            local, reds, "data", bucketed=True,
            transports=transports, tolerances=tolerances,
        )
        return jax.tree_util.tree_map(lambda x: jnp.expand_dims(x, 0), out)

    return jax.jit(
        shard_map(body, mesh=mesh, in_specs=P("data"), out_specs=P("data"),
                  check_rep=False)
    )


def _drive(mesh, state, reds, steps=24, tolerances=None):
    """The tuned driver: re-jit exactly when the decision epoch moves (the
    documented integration pattern — the engine's partition key does the same
    via its autotune token). Returns (last_out, retraces)."""
    per_dev = _per_device(state)
    epoch = at.decision_epoch()
    fn = _make_fn(mesh, reds, tolerances=tolerances)
    retraces = 0
    out = None
    for _ in range(steps):
        if at.decision_epoch() != epoch:
            epoch = at.decision_epoch()
            fn = _make_fn(mesh, reds, tolerances=tolerances)
            retraces += 1
        out = fn(per_dev)
    return jax.tree_util.tree_map(lambda x: np.asarray(x[0]), out), retraces


def _exact_reference(mesh, state, reds):
    exact = {n: "exact" for n in state}
    fn = _make_fn(mesh, reds, transports=exact)
    out = fn(_per_device(state))
    return jax.tree_util.tree_map(lambda x: np.asarray(x[0]), out)


def _rel_err(got, want):
    got, want = np.asarray(got, np.float64), np.asarray(want, np.float64)
    denom = max(np.max(np.abs(want)), 1e-30)
    return float(np.max(np.abs(got - want)) / denom)


# ------------------------------------------------------------- convergence ---
@pytest.mark.mesh8
def test_converges_commits_cheapest_and_stops_retracing(mesh):
    metrics_tpu.set_autotune(True)
    out, retraces = _drive(mesh, _STATE, _REDS, steps=24)
    ctl = at_controller.get_controller()

    big = ctl.buckets[bucket_key("sum", np.dtype("float32"))]
    assert big.phase == "committed"
    # the cheapest admissible rung for a dense 8192-elem f32 sum is int8
    assert big.committed == "int8"
    costs = {r: big.predicted_wire(r) for r in big.ladder()}
    assert costs[big.committed] == min(costs.values())

    counts = ctl.buckets[bucket_key("sum", np.dtype("int32"))]
    assert counts.phase == "committed"
    assert counts.committed == min(counts.ladder(), key=counts.predicted_wire)

    # max buckets have an exact-only ladder: committed on the spot
    mx = ctl.buckets[bucket_key("max", np.dtype("float32"))]
    assert mx.committed == "exact"

    # exploration budget: one retrace per epoch movement (several buckets may
    # decide inside a single trace), decisions bounded by the ladder walk —
    # and the epoch has stopped moving (no flap, no retraces)
    assert 0 < retraces <= len(ctl.decisions) <= 4 * len(at.LADDER)
    epoch = at.decision_epoch()
    _drive(mesh, _STATE, _REDS, steps=4)
    assert at.decision_epoch() == epoch

    # realized error within the (default-tolerance) budget; the exact-only
    # max bucket stays bitwise
    want = _exact_reference(mesh, _STATE, _REDS)
    assert _rel_err(out["big"], want["big"]) <= big.tolerance_for("int8")
    np.testing.assert_array_equal(out["mx"], want["mx"])


@pytest.mark.mesh8
def test_decision_log_replays_bitwise(mesh):
    logs = []
    for _ in range(2):
        metrics_tpu.set_autotune(True, config=PolicyConfig())
        _drive(mesh, _STATE, _REDS, steps=16)
        logs.append(json.dumps(at_controller.get_controller().decisions,
                               sort_keys=True))
        metrics_tpu.set_autotune(False)
    assert logs[0] == logs[1] and logs[0] != "[]"


@pytest.mark.mesh8
def test_pinned_plan_replays_and_never_retraces(mesh):
    metrics_tpu.set_autotune(True)
    tuned_out, _ = _drive(mesh, _STATE, _REDS, steps=16)
    plan = metrics_tpu.export_tuned_plan()
    first_decisions = json.dumps(plan.decisions, sort_keys=True)

    metrics_tpu.set_autotune(plan)
    epoch = at.decision_epoch()
    out, retraces = _drive(mesh, _STATE, _REDS, steps=8)
    assert retraces == 0 and at.decision_epoch() == epoch  # pins add no retraces
    ctl = at_controller.get_controller()
    assert ctl.decisions == []  # nothing explores under a pin
    # the pin replays the converged transports: identical computation,
    # bitwise-identical synced values (lossy rungs included)
    for name in _STATE:
        np.testing.assert_array_equal(out[name], tuned_out[name])
    # and the exported artifact round-trips the decision log bitwise
    assert json.dumps(ctl.export_plan().decisions, sort_keys=True) == first_decisions


# ------------------------------------------------------------- precedence ---
@pytest.mark.mesh8
def test_per_state_declaration_outranks_and_hides_the_bucket(mesh):
    metrics_tpu.set_autotune(True)
    transports = {"big": "bf16"}
    per_dev = _per_device(_STATE)
    fn = _make_fn(mesh, _REDS, transports=transports)
    with sync_mod.count_collectives() as box:
        jax.make_jaxpr(
            lambda st: sync_mod.sync_state(
                st, _REDS, "data", bucketed=True, transports=transports
            ),
            axis_env=[("data", WORLD)],
        )(_STATE)
    fn(per_dev)
    ctl = at_controller.get_controller()
    # the declared bucket syncs bf16 (declaration wins) and the tuner never
    # observes it — declared buckets are the user's call, not the tuner's
    assert "bf16" in box["bytes_by_transport"]
    assert bucket_key("sum", np.dtype("float32")) not in ctl.buckets


@pytest.mark.mesh8
def test_zero_tolerance_buckets_stay_bitwise(mesh):
    metrics_tpu.set_autotune(True)
    tolerances = {"big": 0.0, "counts": 0.0}
    out, _ = _drive(mesh, _STATE, _REDS, steps=20, tolerances=tolerances)
    ctl = at_controller.get_controller()
    big = ctl.buckets[bucket_key("sum", np.dtype("float32"))]
    # a zero tolerance prunes every lossy rung; only lossless transports
    # survive, so the synced values are bitwise-identical to untuned
    assert all(r in ("exact", "sparse_count") for r in big.ladder())
    want = _exact_reference(mesh, _STATE, _REDS)
    for name in _STATE:
        np.testing.assert_array_equal(out[name], want[name])


def test_cadence_precedence_switch_env_tuner(monkeypatch):
    metrics_tpu.set_autotune(True)
    ctl = at_controller.get_controller()
    # drive one bucket to commit with a tolerance wide enough for K>1
    key = bucket_key("sum", np.dtype("float32"))
    for _ in range(8):
        tuner = ctl.buckets.get(key)
        cur = tuner.current if tuner else "exact"
        ctl.observe_bucket(
            "sum", np.dtype("float32"), requested=cur, transport=cur,
            refusal=None, nelems=8192, world=WORLD, tolerance=0.2,
        )
        if ctl.buckets[key].phase == "committed":
            break
    tuned = ctl.cadence()
    assert tuned is not None and tuned > 1
    assert sync_mod.sync_cadence_default() == tuned  # tuner is the fallback
    monkeypatch.setenv("METRICS_TPU_SYNC_EVERY", "5")
    assert sync_mod.sync_cadence_default() == 5      # env outranks the tuner
    sync_mod.set_sync_cadence(3)
    assert sync_mod.sync_cadence_default() == 3      # switch outranks both
    sync_mod.set_sync_cadence(None)
    monkeypatch.delenv("METRICS_TPU_SYNC_EVERY")
    assert sync_mod.sync_cadence_default() == tuned


def test_partition_token_moves_only_on_decisions():
    from metrics_tpu.core.engine import _autotune_token

    metrics_tpu.set_autotune(False)
    assert at.partition_token() == -1 == _autotune_token()
    metrics_tpu.set_autotune(True)
    tok = at.partition_token()
    assert tok == at.decision_epoch() == _autotune_token()
    ctl = at_controller.get_controller()
    ctl.observe_bucket(
        "sum", np.dtype("float32"), requested="exact", transport="exact",
        refusal=None, nelems=8192, world=WORLD,
    )
    assert at.partition_token() > tok  # the decision repartitions the drivers


# ----------------------------------------------------------------- tenancy ---
@pytest.mark.parametrize("tenants", [2, 5])
def test_stacked_buckets_tune_through_n_independent_keys(tenants):
    """TenantSet-stacked state flattens into the same (reduction, dtype)
    buckets as unstacked state, so the tuner's keys — and therefore its
    decisions — are independent of tenant count N and of the leader set."""
    metrics_tpu.set_autotune(True)
    states = {
        "acc": {"tp": jnp.zeros((tenants, 16), jnp.float32)},
        "f1": {"tp": jnp.zeros((tenants, 16), jnp.float32),
               "count": jnp.zeros((tenants,), jnp.int32)},
    }
    reds = {"acc": {"tp": "sum"}, "f1": {"tp": "sum", "count": "sum"}}
    jax.make_jaxpr(
        lambda s: sync_mod.sync_stacked_states(s, reds, "data"),
        axis_env=[("data", WORLD)],
    )(states)
    ctl = at_controller.get_controller()
    assert set(ctl.buckets) == {
        bucket_key("sum", np.dtype("float32")),
        bucket_key("sum", np.dtype("int32")),
    }
    # the bucket sees the flattened element count: every leader's leaves of
    # one (reduction, dtype) ravel into a single tuned bucket
    assert ctl.buckets[bucket_key("sum", np.dtype("float32"))].nelems == 32 * tenants
