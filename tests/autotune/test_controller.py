"""Unit contract of the self-tuning sync controller (``metrics_tpu.autotune``).

Everything here is host-side and deterministic: the policy is a pure function
of the observation sequence (no wall clock, no randomness), admissibility is
delegated to the very same ``sync._gate_transport`` the runtime enforces, and
the analytic wire-byte model (``sync.transport_wire_bytes``) matches what the
codecs tick into ``count_collectives`` byte-for-byte.
"""
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.autotune import (
    AutotuneController,
    CADENCE_LADDER,
    PolicyConfig,
    TunedPlan,
    bucket_key,
)
from metrics_tpu.autotune.controller import _BucketTuner
from metrics_tpu.autotune.history import BucketHistory, BucketSample
from metrics_tpu.parallel import sync as _sync

WORLD = 8


def _observe(tuner, *, requested=None, nelems=8192, world=WORLD, tolerance=None,
             refusal=None, error_scale=1.0):
    """Feed one gate outcome mirroring what ``_sync_bucketed`` reports: the
    tuner's own proposal, admitted (refusal=None) unless stated otherwise."""
    req = requested if requested is not None else tuner.current
    transport = "exact" if refusal is not None else req
    return tuner.observe(
        requested=req, transport=transport, refusal=refusal,
        nelems=nelems, world=world, tolerance=tolerance, error_scale=error_scale,
    )


def _drive_to_commit(tuner, **kw):
    events = []
    for _ in range(32):
        events.extend(_observe(tuner, **kw))
        if tuner.phase == "committed":
            break
    assert tuner.phase == "committed"
    return events


def _tuner(red="sum", dtype="float32", kind="psum", config=None):
    dtype = np.dtype(dtype)
    return _BucketTuner(
        bucket_key(red, dtype, kind), red, dtype, kind,
        config if config is not None else PolicyConfig(),
    )


# --------------------------------------------------------------- admissibility
class TestLadder:
    def test_every_rung_passes_the_runtime_gate(self):
        t = _tuner()
        _observe(t)
        for rung in t.ladder():
            final, refusal = _sync._gate_transport(
                rung, t.red, t.dtype, t.nelems, t.world,
                t.tolerance_for(rung) if rung != "exact" else None,
                kind=t.kind, error_scale=t.max_error_scale,
            )
            assert final == rung and refusal is None

    def test_exact_is_always_admissible(self):
        for red, dtype in (("sum", "float32"), ("max", "float32"), ("sum", "int32")):
            t = _tuner(red=red, dtype=dtype)
            _observe(t, nelems=2)
            assert t.ladder()[0] == "exact"

    def test_f32_sum_bucket_admits_the_quantized_rungs(self):
        t = _tuner()
        _observe(t)
        assert set(t.ladder()) >= {"exact", "bf16", "int8"}

    def test_max_bucket_is_exact_only(self):
        # quantized transports carry sum reductions only; the gate routes a
        # max bucket to exact as inapplicable, so the ladder has one rung
        t = _tuner(red="max")
        _observe(t)
        assert t.ladder() == ("exact",)

    def test_tight_tolerance_prunes_lossy_rungs(self):
        t = _tuner(config=PolicyConfig(error_budget=1e-6))
        _observe(t)
        assert "bf16" not in t.ladder() and "int8" not in t.ladder()

    def test_zero_declared_tolerance_is_exact_only_for_floats(self):
        t = _tuner()
        _observe(t, tolerance=0.0)
        assert all(r in ("exact", "sparse_count") for r in t.ladder())

    def test_error_budget_tightens_but_never_loosens(self):
        # declared 0.002 beats the default 0.05; a *wider* budget must not
        # re-admit what the declaration refused
        wide = _tuner(config=PolicyConfig(error_budget=0.5))
        _observe(wide, tolerance=0.002)
        assert wide.tolerance_for("bf16") == pytest.approx(0.002)


# ------------------------------------------------------------ explore / commit
class TestExploreCommit:
    def test_walks_the_ladder_then_commits_cheapest(self):
        t = _tuner()
        events = _drive_to_commit(t)
        reasons = [e["reason"] for e in events]
        assert reasons[-1] == "commit"
        assert all(r == "explore" for r in reasons[:-1])
        # int8 is the cheapest admissible rung for a dense 8192-elem f32 bucket
        assert t.committed == "int8"
        costs = {r: t.predicted_wire(r) for r in t.ladder()}
        assert costs[t.committed] == min(costs.values())

    def test_one_observation_per_rung_suffices(self):
        # wire bytes are deterministic at trace time: exploration length is
        # |ladder| observations, commit on the |ladder|-th
        t = _tuner()
        events = _drive_to_commit(t)
        assert t.observations == len(t.ladder())
        assert len(events) == len(t.ladder())

    def test_no_world_no_decisions(self):
        t = _tuner()
        assert _observe(t, world=None) == []
        assert t.phase == "explore" and t.current == "exact"

    def test_decision_events_carry_the_audit_fields(self):
        t = _tuner()
        events = _drive_to_commit(t)
        for e in events:
            assert set(e) >= {
                "bucket", "from", "to", "reason", "phase", "observation",
                "cadence", "predicted_wire_bytes", "predicted_error_bound",
            }
            assert e["bucket"] == t.key


# ------------------------------------------------------ dwell / hysteresis ---
class TestNoFlap:
    def test_committed_decision_stands_under_unchanged_costs(self):
        t = _tuner()
        _drive_to_commit(t)
        committed = t.committed
        for _ in range(3 * t.config.min_dwell):
            assert _observe(t) == []
        assert t.committed == committed

    def test_challenger_needs_dwell_and_margin(self):
        t = _tuner(config=PolicyConfig(min_dwell=4, hysteresis=0.10))
        commit = _drive_to_commit(t, nelems=64)[-1]
        # at 64 elements int8 costs one full block (260 B) vs bf16's 128 B, so
        # the gate prunes it (no_byte_win) and bf16 commits. Grow the bucket:
        # int8 amortizes its block overhead into the >10% cheaper challenger...
        assert t.committed == "bf16"
        events = []
        for _ in range(2 * t.config.min_dwell):
            events.extend(_observe(t, nelems=8192))
        switched = [e for e in events if e["reason"] == "hysteresis"]
        assert len(switched) == 1 and switched[0]["to"] == "int8"
        # ...and the dwell floor kept the switch from firing immediately
        assert switched[0]["observation"] - commit["observation"] >= t.config.min_dwell

    def test_sub_margin_win_never_switches(self):
        t = _tuner(config=PolicyConfig(min_dwell=2, hysteresis=0.60))
        _drive_to_commit(t, nelems=64)
        # int8 at 8192 elems beats bf16 by ~47% — under the 60% margin
        for _ in range(6):
            assert _observe(t, nelems=8192) == []


# --------------------------------------------------------------- hard safety
class TestPoison:
    def test_gate_refusal_of_the_proposal_poisons_the_rung(self):
        t = _tuner()
        events = _observe(t)  # exact observed; exploration advances to bf16
        assert events and events[-1]["to"] == "bf16"
        events = _observe(  # the bf16 proposal comes back gate-refused
            t, refusal={"reason": "error_budget", "bound": 1.0, "tolerance": 0.0}
        )
        assert "bf16" in t.poisoned
        assert events and events[-1]["reason"] == "poisoned:error_budget"
        assert events[-1]["to"] == "exact"
        assert "bf16" not in t.ladder()

    def test_poisoned_rung_never_reappears(self):
        t = _tuner()
        _observe(t)  # exact observed; advances to bf16
        _observe(t, refusal={"reason": "error_budget"})  # bf16 refused
        _drive_to_commit(t)
        assert t.committed != "bf16"
        for _ in range(3 * t.config.min_dwell):
            _observe(t)
        assert t.current != "bf16" and "bf16" not in t.ladder()

    def test_poisoning_all_lossy_rungs_lands_on_exact(self):
        t = _tuner()
        _drive_to_commit(t)
        for rung in ("bf16", "int8", "sparse_count"):
            t.poison(rung, "error_spike")
        assert t.current == "exact"

    def test_controller_error_spike_demotes_and_logs(self):
        ctl = AutotuneController(config=PolicyConfig())
        key = bucket_key("sum", np.dtype("float32"))
        for _ in range(8):
            tuner = ctl.buckets.get(key)
            ctl.observe_bucket(
                "sum", np.dtype("float32"), requested=tuner.current if tuner else "exact",
                transport=tuner.current if tuner else "exact", refusal=None,
                nelems=8192, world=WORLD,
            )
            if ctl.buckets[key].phase == "committed":
                break
        committed = ctl.buckets[key].committed
        assert committed in ("bf16", "int8")
        ctl.observe_error("sum", np.dtype("float32"), measured=0.5)
        assert committed in ctl.buckets[key].poisoned
        assert ctl.decisions[-1]["reason"] == "error_spike" or \
            ctl.decisions[-1]["reason"].startswith("poisoned:")

    def test_measured_error_within_tolerance_is_benign(self):
        ctl = AutotuneController(config=PolicyConfig())
        ctl.observe_bucket(
            "sum", np.dtype("float32"), requested="exact", transport="exact",
            refusal=None, nelems=8192, world=WORLD,
        )
        before = list(ctl.decisions)
        ctl.observe_error("sum", np.dtype("float32"), measured=1e-6)
        assert ctl.decisions == before


# ------------------------------------------------------------------- cadence
class TestCadence:
    def test_lossless_transports_take_the_cap(self):
        t = _tuner(config=PolicyConfig(max_cadence=8))
        assert t._cadence_for("exact") == 8
        assert t._cadence_for("sparse_count") == 8

    def test_lossy_cadence_respects_the_compounded_bound(self):
        t = _tuner()
        _observe(t, tolerance=0.2)
        bound = _sync.transport_error_bound("bf16", WORLD, "psum")
        want = max(k for k in CADENCE_LADDER if bound * k <= 0.2)
        assert t._cadence_for("bf16") == want > 1

    def test_tight_tolerance_pins_cadence_to_one(self):
        t = _tuner()
        _observe(t)  # default 0.05 tolerance; 2*bound > 0.05
        assert t._cadence_for("bf16") == 1

    def test_controller_cadence_is_min_over_committed(self):
        ctl = AutotuneController(config=PolicyConfig())
        assert ctl.cadence() is None  # nothing committed yet
        for red, dtype, tol in (("sum", "float32", 0.2), ("sum", "int32", None)):
            key = bucket_key(red, np.dtype(dtype))
            for _ in range(8):
                tuner = ctl.buckets.get(key)
                cur = tuner.current if tuner else "exact"
                ctl.observe_bucket(
                    red, np.dtype(dtype), requested=cur, transport=cur,
                    refusal=None, nelems=8192, world=WORLD, tolerance=tol,
                )
                if ctl.buckets[key].phase == "committed":
                    break
        cadences = [t.cadence for t in ctl.buckets.values()]
        assert ctl.cadence() == min(cadences)


# --------------------------------------------------------------- determinism
class TestDeterminism:
    def _run(self):
        ctl = AutotuneController(config=PolicyConfig(min_dwell=2))
        for step in range(24):
            for red, dtype in (("sum", "float32"), ("sum", "int32"), ("max", "float32")):
                key = bucket_key(red, np.dtype(dtype))
                tuner = ctl.buckets.get(key)
                cur = tuner.current if tuner else "exact"
                ctl.observe_bucket(
                    red, np.dtype(dtype), requested=cur, transport=cur,
                    refusal=None, nelems=4096 if step < 12 else 8192, world=WORLD,
                )
        return ctl

    def test_identical_observations_replay_identical_decisions_bitwise(self):
        a, b = self._run(), self._run()
        assert json.dumps(a.decisions, sort_keys=True) == \
            json.dumps(b.decisions, sort_keys=True)

    def test_export_plan_round_trips(self, tmp_path):
        ctl = self._run()
        plan = ctl.export_plan()
        path = tmp_path / "plan.json"
        plan.save(str(path))
        loaded = TunedPlan.load(str(path))
        assert loaded.to_dict() == plan.to_dict()
        assert TunedPlan.from_dict(plan.to_dict()).to_dict() == plan.to_dict()

    def test_plan_rejects_unknown_version_and_transport(self):
        with pytest.raises(ValueError, match="version"):
            TunedPlan.from_dict({"version": 99})
        with pytest.raises(ValueError, match="transport"):
            TunedPlan.from_dict(
                {"buckets": {"sum|float32|psum": {"transport": "zstd"}}}
            )


# -------------------------------------------------------------- pinned plans
class TestPinned:
    def _plan(self):
        return TunedPlan(
            cadence=4,
            buckets={
                bucket_key("sum", np.dtype("float32")): {"transport": "int8"},
                bucket_key("sum", np.dtype("int32")): {"transport": "bf16"},
            },
        )

    def test_pin_bypasses_exploration(self):
        ctl = AutotuneController(pinned=self._plan())
        assert ctl.transport_for("sum", np.dtype("float32")) == "int8"
        assert ctl.transport_for("sum", np.dtype("int32")) == "bf16"
        ctl.observe_bucket(
            "sum", np.dtype("float32"), requested="int8", transport="int8",
            refusal=None, nelems=8192, world=WORLD,
        )
        assert ctl.buckets == {} and ctl.decisions == []

    def test_uncovered_bucket_pins_to_exact(self):
        ctl = AutotuneController(pinned=self._plan())
        assert ctl.transport_for("mean", np.dtype("float64")) == "exact"

    def test_pinned_cadence_wins(self):
        ctl = AutotuneController(pinned=self._plan())
        assert ctl.cadence() == 4

    def test_pinned_replay_is_bitwise_identical(self):
        # replaying a pinned plan produces the identical (empty) decision
        # sequence and the identical transports — nothing explores
        a = AutotuneController(pinned=self._plan())
        b = AutotuneController(pinned=self._plan())
        for ctl in (a, b):
            for _ in range(8):
                ctl.observe_bucket(
                    "sum", np.dtype("float32"), requested="int8", transport="int8",
                    refusal=None, nelems=8192, world=WORLD,
                )
        assert json.dumps(a.decisions) == json.dumps(b.decisions) == "[]"
        assert a.export_plan().to_dict() == b.export_plan().to_dict()


# ---------------------------------------------------- wire-byte model parity
class TestWireByteModel:
    @pytest.mark.parametrize("transport", ["exact", "bf16", "int8", "sparse_count"])
    @pytest.mark.parametrize("n", [1, 64, 256, 1000, 8192])
    def test_helper_matches_the_codec_tick(self, transport, n):
        """``transport_wire_bytes`` (the tuner's cost model) must equal the
        bytes the codec actually ticks into ``count_collectives`` for the
        transport's *own* collectives — predicted == realized, per transport."""
        dtype = jnp.int32 if transport == "sparse_count" else jnp.float32
        state = {"s": jnp.zeros((n,), dtype)}
        final, refusal = _sync._gate_transport(
            transport, "sum", np.dtype(state["s"].dtype),
            n, WORLD, None if transport == "exact" else _sync.default_tolerance(transport),
        )
        if final != transport:
            pytest.skip(f"gate routes n={n} to {final}: {refusal}")
        with _sync.count_collectives() as box:
            jax.make_jaxpr(
                lambda st: _sync.sync_state(
                    st, {"s": "sum"}, "data", bucketed=True,
                    transports={"s": transport},
                ),
                axis_env=[("data", WORLD)],
            )(state)
        ticked = box["bytes_by_transport"][transport]["wire"]
        assert ticked == _sync.transport_wire_bytes(transport, n, np.dtype(state["s"].dtype))


# ----------------------------------------------------------- history window
class TestHistory:
    def test_window_evicts_oldest(self):
        h = BucketHistory(window=4)
        for i in range(10):
            h.record(BucketSample(ordinal=i, requested="exact", transport="exact",
                                  wire_bytes=i))
        assert h.count() == 4
        assert h.last().wire_bytes == 9

    def test_wire_mean_excludes_refused_samples(self):
        h = BucketHistory(window=8)
        h.record(BucketSample(ordinal=1, requested="bf16", transport="bf16",
                              wire_bytes=100))
        h.record(BucketSample(ordinal=2, requested="bf16", transport="exact",
                              refused=True, refusal_reason="error_budget",
                              wire_bytes=400))
        assert h.wire_mean("bf16") == 100
        assert h.refusals("bf16") == 1
