"""BLEU / SacreBLEU / chrF / TER parity against nltk and sacrebleu oracles."""
import numpy as np
import pytest
from nltk.translate.bleu_score import SmoothingFunction, corpus_bleu as nltk_corpus_bleu
from sacrebleu.metrics import CHRF as SacreCHRF, TER as SacreTER, BLEU as SacreBLEU

from metrics_tpu import BLEUScore, CHRFScore, SacreBLEUScore, TranslationEditRate
from metrics_tpu.ops.text import bleu_score, chrf_score, sacre_bleu_score, translation_edit_rate

# corpus of (preds, list-of-reference-lists)
PREDS = [
    "the cat is on the mat",
    "there is a big tree near the house",
    "hello there general kenobi",
    "it is a guide to action which ensures that the military always obeys the commands of the party",
    "the dog, which was lazy, slept all day; the cat did not.",
]
TARGETS = [
    ["there is a cat on the mat", "a cat is on the mat"],
    ["a big tree is near the house", "there is a tall tree by the house"],
    ["hello there general kenobi", "hi there master kenobi"],
    [
        "it is a guide to action that ensures that the military will forever heed party commands",
        "it is the guiding principle which guarantees the military forces always being under the command of the party",
    ],
    ["the lazy dog slept all day, but the cat did not.", "the dog, being lazy, slept; the cat didn't."],
]


class TestBLEU:
    def test_vs_nltk(self):
        for n_gram in (2, 4):
            weights = tuple(1.0 / n_gram for _ in range(n_gram))
            want = nltk_corpus_bleu(
                [[t.split() for t in refs] for refs in TARGETS],
                [p.split() for p in PREDS],
                weights=weights,
            )
            got = float(bleu_score(PREDS, TARGETS, n_gram=n_gram))
            np.testing.assert_allclose(got, want, atol=1e-6)

    def test_smooth_vs_nltk(self):
        # smooth=True matches nltk smoothing method2 (add-1 for n>1)
        want = nltk_corpus_bleu(
            [[t.split() for t in refs] for refs in TARGETS],
            [p.split() for p in PREDS],
            smoothing_function=SmoothingFunction().method2,
        )
        got = float(bleu_score(PREDS, TARGETS, smooth=True))
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_golden(self):
        got = float(bleu_score(["the cat is on the mat"], [["there is a cat on the mat", "a cat is on the mat"]]))
        np.testing.assert_allclose(got, 0.7598, atol=1e-4)

    def test_module_accumulation(self):
        metric = BLEUScore()
        metric.update(PREDS[:2], TARGETS[:2])
        metric.update(PREDS[2:], TARGETS[2:])
        np.testing.assert_allclose(float(metric.compute()), float(bleu_score(PREDS, TARGETS)), atol=1e-6)

    def test_empty_ngram_returns_zero(self):
        assert float(bleu_score(["xyz"], [["abc"]])) == 0.0


class TestSacreBLEU:
    @pytest.mark.parametrize("tokenize", ["none", "13a", "char", "intl", "zh"])
    @pytest.mark.parametrize("lowercase", [False, True])
    def test_vs_sacrebleu(self, tokenize, lowercase):
        sb = SacreBLEU(tokenize=tokenize, lowercase=lowercase)
        # sacrebleu wants refs transposed: one list per reference position
        max_refs = max(len(r) for r in TARGETS)
        refs_t = [[refs[i] if i < len(refs) else refs[0] for refs in TARGETS] for i in range(max_refs)]
        want = sb.corpus_score(PREDS, refs_t).score / 100.0
        padded_targets = [refs + [refs[0]] * (max_refs - len(refs)) for refs in TARGETS]
        got = float(sacre_bleu_score(PREDS, padded_targets, tokenize=tokenize, lowercase=lowercase))
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_zh_chinese_corpus(self):
        # exercises the CJK ranges beyond ideographs: full-width ASCII, CJK punctuation
        preds = ["猫在垫子上 12.5 度", "hello。world 你好", "ＡＢＣ 你好"]
        targets = [["猫在垫子上有 12.5 度"], ["hello 。 world 你好"], ["ＡＢＣ 你好"]]
        sb = SacreBLEU(tokenize="zh")
        want = sb.corpus_score(preds, [[t[0] for t in targets]]).score / 100.0
        got = float(sacre_bleu_score(preds, targets, tokenize="zh"))
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_module(self):
        metric = SacreBLEUScore()
        metric.update(PREDS, TARGETS)
        np.testing.assert_allclose(float(metric.compute()), float(sacre_bleu_score(PREDS, TARGETS)), atol=1e-6)


class TestCHRF:
    @pytest.mark.parametrize("n_word_order", [0, 2])
    def test_vs_sacrebleu_single_ref(self, n_word_order):
        # we implement the eps-smoothing chrF variant (like the reference)
        single_refs = [[refs[0]] for refs in TARGETS]
        sb = SacreCHRF(word_order=n_word_order, eps_smoothing=True)
        want = sb.corpus_score(PREDS, [[r[0] for r in single_refs]]).score / 100.0
        got = float(chrf_score(PREDS, single_refs, n_word_order=n_word_order))
        np.testing.assert_allclose(got, want, atol=1e-4)

    def test_golden_multi_ref(self):
        got = float(chrf_score(["the cat is on the mat"], [["there is a cat on the mat", "a cat is on the mat"]]))
        np.testing.assert_allclose(got, 0.8640, atol=1e-3)

    def test_module_accumulation(self):
        metric = CHRFScore()
        metric.update(PREDS[:2], TARGETS[:2])
        metric.update(PREDS[2:], TARGETS[2:])
        np.testing.assert_allclose(float(metric.compute()), float(chrf_score(PREDS, TARGETS)), atol=1e-6)

    def test_sentence_level_scores(self):
        score, sentence_scores = chrf_score(PREDS, TARGETS, return_sentence_level_score=True)
        assert sentence_scores.shape == (len(PREDS),)
        assert float(sentence_scores[2]) > 0.9  # near-exact match sentence

    def test_arg_validation(self):
        with pytest.raises(ValueError):
            chrf_score(PREDS, TARGETS, n_char_order=0)
        with pytest.raises(ValueError):
            chrf_score(PREDS, TARGETS, n_word_order=-1)
        with pytest.raises(ValueError):
            chrf_score(PREDS, TARGETS, beta=-1.0)


class TestTER:
    @pytest.mark.parametrize("normalize", [False, True])
    @pytest.mark.parametrize("lowercase", [False, True])
    def test_vs_sacrebleu_single_ref(self, normalize, lowercase):
        sb = SacreTER(normalized=normalize, case_sensitive=not lowercase)
        want = sb.corpus_score(PREDS, [[refs[0] for refs in TARGETS]]).score / 100.0
        got = float(
            translation_edit_rate(PREDS, [[refs[0]] for refs in TARGETS], normalize=normalize, lowercase=lowercase)
        )
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_vs_sacrebleu_multi_ref(self):
        sb = SacreTER()
        max_refs = max(len(r) for r in TARGETS)
        refs_t = [[refs[i] if i < len(refs) else refs[0] for refs in TARGETS] for i in range(max_refs)]
        want = sb.corpus_score(PREDS, refs_t).score / 100.0
        padded_targets = [refs + [refs[0]] * (max_refs - len(refs)) for refs in TARGETS]
        got = float(translation_edit_rate(PREDS, padded_targets))
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_golden(self):
        got = float(
            translation_edit_rate(["the cat is on the mat"], [["there is a cat on the mat", "a cat is on the mat"]])
        )
        np.testing.assert_allclose(got, 0.1538, atol=1e-4)

    def test_module_accumulation(self):
        metric = TranslationEditRate()
        metric.update(PREDS[:2], TARGETS[:2])
        metric.update(PREDS[2:], TARGETS[2:])
        np.testing.assert_allclose(
            float(metric.compute()), float(translation_edit_rate(PREDS, TARGETS)), atol=1e-6
        )

    def test_shifts_reduce_edits(self):
        # a pure transposition should cost 1 shift, not multiple substitutions
        got = float(translation_edit_rate(["b c d e a"], [["a b c d e"]]))
        np.testing.assert_allclose(got, 1 / 5, atol=1e-6)
