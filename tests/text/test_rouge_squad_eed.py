"""ROUGE (oracle: google rouge_score), SQuAD, and EED parity tests."""
import numpy as np
import pytest
from rouge_score.rouge_scorer import RougeScorer
from rouge_score.scoring import BootstrapAggregator

from metrics_tpu import ExtendedEditDistance, ROUGEScore, SQuAD
from metrics_tpu.ops.text import extended_edit_distance, rouge_score as tm_rouge_score, squad

PREDS = [
    "the cat was found under the bed",
    "my life is a drama",
    "the quick brown fox jumps over the lazy dog",
]
TARGETS = [
    "the cat was under the bed",
    "my life is a mess and a drama",
    "a quick brown fox jumped over lazy dogs",
]

ROUGE_KEYS = ("rouge1", "rouge2", "rougeL", "rougeLsum")


def _oracle_rouge(preds, targets, use_stemmer=False):
    scorer = RougeScorer(list(ROUGE_KEYS), use_stemmer=use_stemmer)
    aggregator = BootstrapAggregator()
    for p, t in zip(preds, targets):
        aggregator.add_scores(scorer.score(t, p))
    # mid of bootstrap == mean only approximately; compute plain means instead
    out = {}
    per_sentence = [scorer.score(t, p) for p, t in zip(preds, targets)]
    for key in ROUGE_KEYS:
        out[f"{key}_precision"] = np.mean([s[key].precision for s in per_sentence])
        out[f"{key}_recall"] = np.mean([s[key].recall for s in per_sentence])
        out[f"{key}_fmeasure"] = np.mean([s[key].fmeasure for s in per_sentence])
    return out


class TestROUGE:
    @pytest.mark.parametrize("use_stemmer", [False, True])
    def test_vs_rouge_score(self, use_stemmer):
        want = _oracle_rouge(PREDS, TARGETS, use_stemmer=use_stemmer)
        got = tm_rouge_score(PREDS, TARGETS, use_stemmer=use_stemmer, rouge_keys=ROUGE_KEYS)
        for key, val in want.items():
            np.testing.assert_allclose(float(got[key]), val, atol=1e-6, err_msg=key)

    def test_module_accumulation(self):
        metric = ROUGEScore(rouge_keys=ROUGE_KEYS)
        metric.update(PREDS[:2], TARGETS[:2])
        metric.update(PREDS[2:], TARGETS[2:])
        got = metric.compute()
        want = tm_rouge_score(PREDS, TARGETS, rouge_keys=ROUGE_KEYS)
        for key in want:
            np.testing.assert_allclose(float(got[key]), float(want[key]), atol=1e-6)

    def test_multi_reference_best(self):
        got = tm_rouge_score(
            ["the cat is on the mat"],
            [["a cat sat on a mat", "the cat is on the mat"]],
            accumulate="best",
            rouge_keys="rouge1",
        )
        np.testing.assert_allclose(float(got["rouge1_fmeasure"]), 1.0, atol=1e-6)

    def test_multi_reference_avg(self):
        got = tm_rouge_score(
            ["the cat is on the mat"],
            [["the cat is on the mat", "the cat is on the mat"]],
            accumulate="avg",
            rouge_keys="rouge1",
        )
        np.testing.assert_allclose(float(got["rouge1_fmeasure"]), 1.0, atol=1e-6)

    def test_invalid_key_raises(self):
        with pytest.raises(ValueError):
            tm_rouge_score(PREDS, TARGETS, rouge_keys="rouge42")

    def test_pickle_roundtrip_with_stemmer(self):
        import pickle

        metric = ROUGEScore(use_stemmer=True, rouge_keys="rouge1")
        metric.update(PREDS, TARGETS)
        metric2 = pickle.loads(pickle.dumps(metric))
        got, want = metric2.compute(), metric.compute()
        np.testing.assert_allclose(float(got["rouge1_fmeasure"]), float(want["rouge1_fmeasure"]))


class TestSQuAD:
    def test_perfect(self):
        preds = [{"prediction_text": "1976", "id": "56e10a3be3433e1400422b22"}]
        target = [{"answers": {"answer_start": [97], "text": ["1976"]}, "id": "56e10a3be3433e1400422b22"}]
        got = squad(preds, target)
        np.testing.assert_allclose(float(got["exact_match"]), 100.0)
        np.testing.assert_allclose(float(got["f1"]), 100.0)

    def test_partial_f1(self):
        preds = {"prediction_text": "big red cat", "id": "1"}
        target = {"answers": {"answer_start": [0], "text": ["big cat"]}, "id": "1"}
        got = squad(preds, target)
        assert float(got["exact_match"]) == 0.0
        # overlap = {big, cat}: p = 2/3, r = 2/2 -> f1 = 0.8
        np.testing.assert_allclose(float(got["f1"]), 80.0, atol=1e-4)

    def test_max_over_ground_truths(self):
        preds = {"prediction_text": "Paris", "id": "q"}
        target = {"answers": {"answer_start": [0, 5], "text": ["London", "Paris"]}, "id": "q"}
        got = squad(preds, target)
        np.testing.assert_allclose(float(got["exact_match"]), 100.0)

    def test_module_accumulation(self):
        metric = SQuAD()
        metric.update({"prediction_text": "a", "id": "1"}, {"answers": {"text": ["a"]}, "id": "1"})
        metric.update({"prediction_text": "b", "id": "2"}, {"answers": {"text": ["c"]}, "id": "2"})
        got = metric.compute()
        np.testing.assert_allclose(float(got["exact_match"]), 50.0)

    def test_bad_keys_raise(self):
        with pytest.raises(KeyError):
            squad({"wrong": "x", "id": "1"}, {"answers": {"text": ["a"]}, "id": "1"})
        with pytest.raises(KeyError):
            squad({"prediction_text": "x", "id": "1"}, {"id": "1"})


class TestEED:
    def test_reference_golden(self):
        preds = ["this is the prediction", "here is an other sample"]
        target = ["this is the reference", "here is another one"]
        got = float(extended_edit_distance(preds, target))
        np.testing.assert_allclose(got, 0.3078, atol=1e-4)

    def test_identical_is_near_zero(self):
        # EED keeps a small coverage penalty even for identical strings
        got = float(extended_edit_distance(["same text"], [["same text"]]))
        assert 0.0 < got < 0.05

    def test_multi_ref_takes_best(self):
        best = float(extended_edit_distance(["good morning"], [["good morning", "totally different"]]))
        ident = float(extended_edit_distance(["good morning"], [["good morning"]]))
        assert best == ident

    def test_module_matches_functional(self):
        preds = ["this is the prediction", "here is an other sample"]
        target = ["this is the reference", "here is another one"]
        metric = ExtendedEditDistance()
        metric.update(preds[:1], [[target[0]]])
        metric.update(preds[1:], [[target[1]]])
        np.testing.assert_allclose(float(metric.compute()), float(extended_edit_distance(preds, target)), atol=1e-6)

    def test_ja_language_path(self):
        got = float(extended_edit_distance(["こんにちは"], [["こんにちは"]], language="ja"))
        assert 0.0 <= got < 0.1

    def test_arg_validation(self):
        with pytest.raises(ValueError):
            extended_edit_distance(["a"], [["b"]], alpha=-1.0)
        with pytest.raises(ValueError):
            ExtendedEditDistance(language="de")
