"""DDP grid for the text domain.

Reference parity: every reference text class test runs with ddp=[False, True]
via torch.distributed host gathers (tests/helpers/testers.py:398-439; e.g.
tests/text/test_wer.py). Text updates consume python strings, so the
distributed path here is the host-gather analog: per-rank instances, deep
``merge_states`` fold (tests/helpers/testers.py ``merge_world``), and the
merged compute must EXACTLY equal a single process that saw all data.
"""
import numpy as np
import pytest

import metrics_tpu as M
from tests.helpers.testers import merge_world

WORLD = 4

_CORPUS = [
    "the cat sat on the mat",
    "a quick brown fox jumps over the lazy dog",
    "hello world this is a test",
    "jax compiles to the tpu",
    "metrics are computed in parallel",
    "the rain in spain stays mainly in the plain",
    "to be or not to be that is the question",
    "all happy families are alike",
]
_REFS = [
    "the cat sits on the mat",
    "a fast brown fox jumped over a lazy dog",
    "hello world this was a test",
    "jax compiled for the tpu",
    "metrics were computed in parallel",
    "the rain in spain falls mainly on the plain",
    "to be or not to be that was a question",
    "every happy family is alike",
]

# (class, preds-shape) — flat targets vs list-of-references targets
_FLAT = [
    M.WordErrorRate, M.CharErrorRate, M.MatchErrorRate, M.WordInfoLost,
    M.WordInfoPreserved, M.ExtendedEditDistance,
]
_NESTED = [M.BLEUScore, M.SacreBLEUScore, M.CHRFScore, M.TranslationEditRate]


def _shards(seq, world=WORLD):
    return [seq[r::world] for r in range(world)]


@pytest.mark.parametrize("metric_cls", _FLAT + _NESTED, ids=lambda c: c.__name__)
def test_text_ddp_merge_equals_single_process(metric_cls):
    nested = metric_cls in _NESTED
    targets = [[r] for r in _REFS] if nested else _REFS

    single = metric_cls()
    single.update(_CORPUS, targets)
    want = single.compute()

    ranks = [metric_cls() for _ in range(WORLD)]
    for rank, (p_shard, t_shard) in enumerate(zip(_shards(_CORPUS), _shards(targets))):
        ranks[rank].update(p_shard, t_shard)
    got = merge_world(ranks).compute()

    np.testing.assert_allclose(
        np.asarray(got, dtype=np.float64), np.asarray(want, dtype=np.float64), rtol=1e-6,
    )


def test_rouge_ddp_merge_equals_single_process():
    single = M.ROUGEScore()
    single.update(_CORPUS, _REFS)
    want = single.compute()

    ranks = [M.ROUGEScore() for _ in range(WORLD)]
    for rank, (p, t) in enumerate(zip(_shards(_CORPUS), _shards(_REFS))):
        ranks[rank].update(p, t)
    got = merge_world(ranks).compute()

    assert set(got) == set(want)
    for k in want:
        np.testing.assert_allclose(float(got[k]), float(want[k]), rtol=1e-6)


def test_squad_ddp_merge_equals_single_process():
    preds = [dict(prediction_text=p, id=str(i)) for i, p in enumerate(_CORPUS)]
    targets = [
        dict(answers=dict(text=[r], answer_start=[0]), id=str(i)) for i, r in enumerate(_REFS)
    ]
    single = M.SQuAD()
    single.update(preds, targets)
    want = single.compute()

    ranks = [M.SQuAD() for _ in range(WORLD)]
    for rank in range(WORLD):
        ranks[rank].update(preds[rank::WORLD], targets[rank::WORLD])
    got = merge_world(ranks).compute()

    for k in want:
        np.testing.assert_allclose(float(got[k]), float(want[k]), rtol=1e-6)


def test_bertscore_ddp_merge_equals_single_process():
    import jax.numpy as jnp

    def fwd(model, batch):
        ids = batch["input_ids"]
        # deterministic embedding of the token id (any fixed fn works)
        base = jnp.arange(8, dtype=jnp.float32)[None, None, :]
        return jnp.sin(base * (1.0 + jnp.asarray(ids, jnp.float32)[..., None]))

    class Tok:
        def __call__(self, sentences, **kwargs):
            ids = np.zeros((len(sentences), 8), dtype=np.int32)
            mask = np.zeros((len(sentences), 8), dtype=np.int32)
            for i, s in enumerate(sentences):
                for j, tok in enumerate(s.split()[:8]):
                    ids[i, j] = (hash(tok) % 97) + 1
                    mask[i, j] = 1
            return {"input_ids": jnp.asarray(ids), "attention_mask": jnp.asarray(mask)}

    def make():
        return M.BERTScore(model=object(), user_forward_fn=fwd, user_tokenizer=Tok())

    single = make()
    single.update(_CORPUS, _REFS)
    want = single.compute()

    ranks = [make() for _ in range(WORLD)]
    for rank in range(WORLD):
        ranks[rank].update(_CORPUS[rank::WORLD], _REFS[rank::WORLD])
    got = merge_world(ranks).compute()

    # scores are per-sentence; ddp striding reorders them — compare as sets
    for k in ("precision", "recall", "f1"):
        np.testing.assert_allclose(
            sorted(np.asarray(got[k], dtype=np.float64)),
            sorted(np.asarray(want[k], dtype=np.float64)),
            atol=1e-5,
        )
