"""WER/CER/MER/WIL/WIP parity tests.

Oracles: a test-local plain-python Levenshtein (independent of the package's
vectorized device kernel) plus the reference implementation's published
docstring goldens (torchmetrics/functional/text/{wer,mer,wil,wip,cer}.py).
"""
import random

import numpy as np
import pytest

from metrics_tpu import CharErrorRate, MatchErrorRate, WordErrorRate, WordInfoLost, WordInfoPreserved
from metrics_tpu.ops.text import (
    char_error_rate,
    match_error_rate,
    word_error_rate,
    word_information_lost,
    word_information_preserved,
)
from metrics_tpu.ops.text.helper import _edit_distance_host, batch_edit_distances

PREDS = ["this is the prediction", "there is an other sample"]
TARGET = ["this is the reference", "there is another one"]

BATCHES = [
    (["hello world", "the quick brown fox"], ["hello duck", "the quick brown fox jumps"]),
    (["a b c d", "x"], ["a b d", "y z"]),
]


def _oracle_edit(a, b):
    # textbook DP, O(len(a)*len(b)) ints
    dp = [[0] * (len(b) + 1) for _ in range(len(a) + 1)]
    for i in range(len(a) + 1):
        dp[i][0] = i
    for j in range(len(b) + 1):
        dp[0][j] = j
    for i in range(1, len(a) + 1):
        for j in range(1, len(b) + 1):
            cost = 0 if a[i - 1] == b[j - 1] else 1
            dp[i][j] = min(dp[i - 1][j] + 1, dp[i][j - 1] + 1, dp[i - 1][j - 1] + cost)
    return dp[-1][-1]


def _oracle_wer(preds, target):
    errs = sum(_oracle_edit(p.split(), t.split()) for p, t in zip(preds, target))
    total = sum(len(t.split()) for t in target)
    return errs / total


class TestEditDistanceKernel:
    """The batched device kernel must agree with the plain DP on random data."""

    def test_random_token_pairs(self):
        rng = random.Random(42)
        preds, targets = [], []
        for _ in range(20):
            vocab = ["a", "b", "c", "d", "e"]
            preds.append([rng.choice(vocab) for _ in range(rng.randint(0, 12))])
            targets.append([rng.choice(vocab) for _ in range(rng.randint(0, 15))])
        got = np.asarray(batch_edit_distances(preds, targets))
        want = np.asarray([_oracle_edit(p, t) for p, t in zip(preds, targets)])
        np.testing.assert_array_equal(got, want)

    def test_empty_cases(self):
        got = np.asarray(batch_edit_distances([[], ["a", "b"]], [["x"], []]))
        np.testing.assert_array_equal(got, [1, 2])

    def test_host_fallback_matches(self):
        assert _edit_distance_host(list("kitten"), list("sitting")) == 3


@pytest.mark.parametrize("preds,target", BATCHES + [(PREDS, TARGET)])
def test_wer_functional(preds, target):
    np.testing.assert_allclose(float(word_error_rate(preds, target)), _oracle_wer(preds, target), atol=1e-6)


def test_docstring_goldens():
    # published values from the reference implementation's doctests
    np.testing.assert_allclose(float(word_error_rate(PREDS, TARGET)), 0.5, atol=1e-4)
    np.testing.assert_allclose(float(match_error_rate(PREDS, TARGET)), 0.4444, atol=1e-4)
    np.testing.assert_allclose(float(word_information_lost(PREDS, TARGET)), 0.6528, atol=1e-4)
    np.testing.assert_allclose(float(word_information_preserved(PREDS, TARGET)), 0.3472, atol=1e-4)
    np.testing.assert_allclose(float(char_error_rate(PREDS, TARGET)), 0.3415, atol=1e-4)


@pytest.mark.parametrize(
    "metric_cls,fn",
    [
        (WordErrorRate, word_error_rate),
        (CharErrorRate, char_error_rate),
        (MatchErrorRate, match_error_rate),
        (WordInfoLost, word_information_lost),
        (WordInfoPreserved, word_information_preserved),
    ],
)
def test_modular_accumulation(metric_cls, fn):
    """Batched updates accumulate to the whole-corpus functional value."""
    metric = metric_cls()
    all_preds, all_target = [], []
    for preds, target in BATCHES:
        metric.update(preds, target)
        all_preds += preds
        all_target += target
    np.testing.assert_allclose(float(metric.compute()), float(fn(all_preds, all_target)), atol=1e-6)


def test_merge_states_equals_single_corpus():
    """Pure-protocol merge (the DDP path) equals single-device accumulation."""
    metric = WordErrorRate()
    s1 = metric.update_state(metric.init_state(), BATCHES[0][0], BATCHES[0][1])
    s2 = metric.update_state(metric.init_state(), BATCHES[1][0], BATCHES[1][1])
    merged = metric.merge_states(s1, s2)
    got = metric.compute_state(merged)
    want = word_error_rate(BATCHES[0][0] + BATCHES[1][0], BATCHES[0][1] + BATCHES[1][1])
    np.testing.assert_allclose(float(got), float(want), atol=1e-6)


def test_single_string_inputs():
    assert float(word_error_rate("hello world", "hello world")) == 0.0
    assert float(char_error_rate("abc", "abc")) == 0.0
