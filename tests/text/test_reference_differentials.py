"""Text option surfaces pinned directly against the reference implementation.

BLEU's smoothing/brevity-penalty and SQuAD's normalization pipeline are
reference-defined (nltk/sacrebleu approximate but don't define them); these
cells assert exact agreement with the reference running live on identical
corpora (reference functional/text/bleu.py, squad.py, chrf.py, ter.py,
cer.py/wer.py/mer.py/wil.py/wip.py).
"""
import numpy as np
import pytest

import metrics_tpu.functional as mtf


def _ref():
    from tests.conftest import reference_functional

    return reference_functional()


_PREDS = ["the cat is on the mat", "a quick brown fox jumps"]
_TARGETS = [
    ["there is a cat on the mat", "the cat sits on the mat"],
    ["the quick brown fox jumps over the dog", "a fast brown fox leaps"],
]


@pytest.mark.parametrize("smooth", [False, True], ids=["plain", "smooth"])
@pytest.mark.parametrize("n_gram", [1, 2, 3, 4])
def test_bleu_vs_reference(n_gram, smooth):
    torch, F = _ref()
    ours = float(mtf.bleu_score(_PREDS, _TARGETS, n_gram=n_gram, smooth=smooth))
    want = float(F.bleu_score(_PREDS, _TARGETS, n_gram=n_gram, smooth=smooth))
    np.testing.assert_allclose(ours, want, atol=1e-6)


def test_squad_vs_reference():
    torch, F = _ref()
    preds = [{"prediction_text": "1976", "id": "56e10a3be3433e1400422b22"},
             {"prediction_text": "the Panthers", "id": "q2"}]
    target = [
        {"answers": {"answer_start": [97], "text": ["1976"]}, "id": "56e10a3be3433e1400422b22"},
        {"answers": {"answer_start": [1], "text": ["Carolina Panthers", "Panthers"]}, "id": "q2"},
    ]
    ours = mtf.squad(preds, target)
    want = F.squad(preds, target)
    for key in ("exact_match", "f1"):
        np.testing.assert_allclose(float(ours[key]), float(want[key]), atol=1e-6)


@pytest.mark.parametrize(
    "name",
    ["char_error_rate", "word_error_rate", "match_error_rate", "word_information_lost", "word_information_preserved"],
)
def test_error_rates_vs_reference(name):
    torch, F = _ref()
    preds = ["this is the prediction", "there is an other sample", ""]
    target = ["this is the reference", "there is another one", "non empty"]
    ours = float(getattr(mtf, name)(preds, target))
    want = float(getattr(F, name)(preds, target))
    np.testing.assert_allclose(ours, want, atol=1e-6)


@pytest.mark.parametrize("return_sentence_level", [False, True], ids=["corpus", "sentence"])
def test_chrf_vs_reference(return_sentence_level):
    torch, F = _ref()
    if return_sentence_level:
        ours_c, ours_s = mtf.chrf_score(_PREDS, _TARGETS, return_sentence_level_score=True)
        want_c, want_s = F.chrf_score(_PREDS, _TARGETS, return_sentence_level_score=True)
        np.testing.assert_allclose(np.asarray(ours_s), np.asarray(want_s), atol=1e-6)
    else:
        ours_c = mtf.chrf_score(_PREDS, _TARGETS)
        want_c = F.chrf_score(_PREDS, _TARGETS)
    np.testing.assert_allclose(float(ours_c), float(want_c), atol=1e-6)


# punctuation + CJK text so the normalize/asian_support tokenizer branches
# actually fire (all-lowercase-Latin inputs make the grid vacuous)
_TER_PREDS = ["hello, world! this is a test...", "\u6771\u4eac\u30bf\u30ef\u30fc\u306f\u9ad8\u3044 (tall)"]
_TER_TARGETS = [["hello world, this is the test.", "hello, world: it is a test!"], ["\u6771\u4eac\u30bf\u30ef\u30fc\u306f\u3068\u3066\u3082\u9ad8\u3044 (very tall)"]]


@pytest.mark.parametrize("asian_support", [False, True], ids=["latin", "asian"])
@pytest.mark.parametrize("normalize", [False, True], ids=["raw", "normalize"])
def test_ter_vs_reference(normalize, asian_support):
    torch, F = _ref()
    ours = float(
        mtf.translation_edit_rate(_TER_PREDS, _TER_TARGETS, normalize=normalize, asian_support=asian_support)
    )
    want = float(
        F.translation_edit_rate(_TER_PREDS, _TER_TARGETS, normalize=normalize, asian_support=asian_support)
    )
    np.testing.assert_allclose(ours, want, atol=1e-6)
