"""Property-based invariants for the text error-rate family (hypothesis).

The device-side batched Levenshtein kernel must honor the metric axioms the
eager reference math has by construction: identity, bounds, and symmetry of
the underlying distance — searched over random corpora instead of fixtures.
"""
import pytest

pytest.importorskip("hypothesis", reason="property-based tests need the `test` extra (pip install metrics-tpu[test])")
from hypothesis import HealthCheck, given, settings
from hypothesis import strategies as st

from metrics_tpu.ops import char_error_rate, match_error_rate, word_error_rate, word_information_preserved

SETTINGS = settings(
    max_examples=30,
    deadline=None,
    suppress_health_check=[HealthCheck.too_slow],
    derandomize=True,
)

words = st.text(alphabet="abcde", min_size=1, max_size=5)
sentences = st.lists(words, min_size=1, max_size=6).map(" ".join)
corpora = st.lists(sentences, min_size=1, max_size=4)


@SETTINGS
@given(corpus=corpora)
def test_error_rates_identity(corpus):
    assert float(word_error_rate(corpus, corpus)) == 0.0
    assert float(char_error_rate(corpus, corpus)) == 0.0
    assert float(match_error_rate(corpus, corpus)) == 0.0
    assert float(word_information_preserved(corpus, corpus)) == pytest.approx(1.0, abs=1e-6)


@SETTINGS
@given(preds=corpora, target=corpora)
def test_error_rates_bounds(preds, target):
    n = min(len(preds), len(target))
    preds, target = preds[:n], target[:n]
    assert float(char_error_rate(preds, target)) >= 0.0
    # MER is normalized by max(ref, hyp) words so it cannot exceed 1
    assert 0.0 <= float(match_error_rate(preds, target)) <= 1.0
    assert 0.0 <= float(word_information_preserved(preds, target)) <= 1.0 + 1e-6


@SETTINGS
@given(preds=corpora, target=corpora)
def test_wer_cer_swap_scales_by_length_ratio(preds, target):
    """Levenshtein distance is symmetric, so swapping hypothesis and reference
    rescales the rate by the corpus length ratio: wer(a,b)*len_b = wer(b,a)*len_a."""
    n = min(len(preds), len(target))
    preds, target = preds[:n], target[:n]
    ref_words = sum(len(s.split()) for s in target)
    hyp_words = sum(len(s.split()) for s in preds)
    lhs = float(word_error_rate(preds, target)) * ref_words
    rhs = float(word_error_rate(target, preds)) * hyp_words
    assert lhs == pytest.approx(rhs, rel=1e-5)
