"""BERTScore default-path tests: the ``FlaxAutoModel``/``AutoTokenizer`` route.

The hub is unreachable offline, but the default path only needs a *directory*,
so these tests build a tiny BERT (2 layers, d=16) with ``transformers``, save
it locally, and point ``model_name_or_path`` at it — exercising the exact code
users hit with a downloaded checkpoint (text/bert.py:93-108; reference analog
torchmetrics/text/bert.py:41 with its default-model branch).

The differential test converts the same flax weights to torch and runs the
reference implementation on them, so both frameworks score identical inputs
with identical weights.
"""
import os

import numpy as np
import pytest

from tests.conftest import import_reference_torchmetrics

transformers = pytest.importorskip("transformers")

from metrics_tpu import BERTScore  # noqa: E402

VOCAB = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]", "hello", "there", "master", "kenobi", "general"]
PREDS = ["hello there", "master kenobi"]
TARGET = ["hello there", "hello kenobi general"]


@pytest.fixture(scope="module")
def tiny_bert_dir(tmp_path_factory):
    os.environ.setdefault("HF_HUB_OFFLINE", "1")
    os.environ.setdefault("TRANSFORMERS_OFFLINE", "1")
    path = tmp_path_factory.mktemp("tiny_bert")
    with open(path / "vocab.txt", "w") as f:
        f.write("\n".join(VOCAB))
    tokenizer = transformers.BertTokenizer(str(path / "vocab.txt"))
    tokenizer.save_pretrained(str(path))
    config = transformers.BertConfig(
        vocab_size=len(VOCAB),
        hidden_size=16,
        num_hidden_layers=2,
        num_attention_heads=2,
        intermediate_size=32,
        max_position_embeddings=32,
    )
    try:
        # save BOTH framework formats with identical weights (torch first —
        # the pt->flax conversion path is the supported one); the differential
        # below then loads each natively
        import torch  # noqa: F401

        transformers.BertModel(config).save_pretrained(str(path))
        transformers.FlaxBertModel.from_pretrained(str(path), from_pt=True).save_pretrained(str(path))
    except Exception:
        transformers.FlaxBertModel(config, seed=0).save_pretrained(str(path))
    return str(path)


def test_default_model_path_scores(tiny_bert_dir):
    metric = BERTScore(model_name_or_path=tiny_bert_dir, max_length=16)
    metric.update(PREDS, TARGET)
    out = metric.compute()
    assert set(out) == {"precision", "recall", "f1"}
    # the identical pair must score a perfect match; the different pair must not
    for key in out:
        assert out[key][0] == pytest.approx(1.0, abs=1e-4)
        assert 0.0 < out[key][1] < 1.0 - 1e-4


def test_default_model_path_idf_and_layers(tiny_bert_dir):
    metric = BERTScore(model_name_or_path=tiny_bert_dir, max_length=16, idf=True, num_layers=1)
    metric.update(PREDS, TARGET)
    out = metric.compute()
    assert out["f1"][0] == pytest.approx(1.0, abs=1e-4)


def test_default_model_path_matches_reference(tiny_bert_dir):
    """Same tiny weights through both full pipelines (flax here, torch there)."""
    pytest.importorskip("torch")
    if not any(name.startswith(("pytorch_model", "model.safetensors")) for name in os.listdir(tiny_bert_dir)):
        pytest.skip("no torch-format weights saved alongside the flax ones")
    try:
        tm = import_reference_torchmetrics()
    except Exception as err:  # pragma: no cover - environment-specific
        pytest.skip(f"reference torchmetrics unavailable: {err}")

    ours = BERTScore(model_name_or_path=tiny_bert_dir, max_length=16, num_layers=2)
    ours.update(PREDS, TARGET)
    got = ours.compute()

    theirs = tm.text.bert.BERTScore(
        model_name_or_path=tiny_bert_dir, max_length=16, num_layers=2, num_threads=0
    )
    theirs.update(PREDS, TARGET)
    want = theirs.compute()

    for key in ("precision", "recall", "f1"):
        np.testing.assert_allclose(
            np.asarray(got[key], dtype=np.float64),
            np.asarray([float(x) for x in want[key]], dtype=np.float64),
            atol=1e-4,
            err_msg=key,
        )
