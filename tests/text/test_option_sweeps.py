"""Dense option sweeps for the text family vs package oracles.

Reference analog: each reference text test file sweeps its metric's full
option surface against the upstream package (tests/text/test_bleu.py
n_gram/smooth, test_chrf.py char/word orders + whitespace, test_ter.py the
four normalization flags, test_rouge.py keys/stemmer/accumulate). Here the
sweeps run on a corpus with multi-reference targets, unicode, punctuation,
casing, and degenerate strings — the inputs where option handling actually
changes the answer.
"""
import numpy as np
import pytest
from sacrebleu.metrics import BLEU as SacreBLEU, CHRF as SacreCHRF, TER as SacreTER

import metrics_tpu as M

_PREDS = [
    "the quick brown Fox jumps over the lazy dog!",
    "hello, world — this is a TEST.",
    "El rápido zorro marrón salta.",
    "a shorter test sentence here",
    "punctuation, everywhere; truly: everywhere!",
]
_TARGETS = [
    ["the quick brown fox jumped over a lazy dog.", "a quick brown fox jumps over the lazy dog"],
    ["hello world, this was a test!", "hello world this is a test"],
    ["El zorro marrón rápido salta.", "Un zorro rápido salta."],
    ["a short test sentence here", "a shorter sentence"],
    ["punctuation everywhere, truly everywhere", "punctuation, everywhere; truly everywhere!"],
]


@pytest.mark.parametrize("n_gram", [1, 2, 3, 4])
@pytest.mark.parametrize("smooth", [False, True], ids=["plain", "smooth"])
def test_bleu_option_sweep(n_gram, smooth):
    got = float(M.BLEUScore(n_gram=n_gram, smooth=smooth)(_PREDS, _TARGETS))
    # nltk corpus_bleu with uniform weights and method1 smoothing replicates
    # the reference's torch implementation on whitespace tokens
    from nltk.translate.bleu_score import SmoothingFunction, corpus_bleu

    weights = tuple(1.0 / n_gram for _ in range(n_gram))
    refs = [[r.split() for r in t] for t in _TARGETS]
    hyps = [p.split() for p in _PREDS]
    # smooth=True implements add-1 counts for n>1 == nltk method2 (the
    # reference's convention, see tests/text/test_bleu_chrf_ter.py)
    sm = SmoothingFunction().method2 if smooth else SmoothingFunction().method0
    want = corpus_bleu(refs, hyps, weights=weights, smoothing_function=sm)
    np.testing.assert_allclose(got, want, atol=1e-4)


@pytest.mark.parametrize("lowercase", [False, True], ids=["cased", "lowercase"])
@pytest.mark.parametrize("tokenize", ["13a", "none", "char"])
def test_sacrebleu_option_sweep(tokenize, lowercase):
    got = float(M.SacreBLEUScore(tokenize=tokenize, lowercase=lowercase)(_PREDS, _TARGETS))
    want = (
        SacreBLEU(tokenize=tokenize, lowercase=lowercase)
        .corpus_score(_PREDS, [[t[i] for t in _TARGETS] for i in range(2)])
        .score
        / 100.0
    )
    np.testing.assert_allclose(got, want, atol=1e-4)


@pytest.mark.parametrize("whitespace", [False, True], ids=["nospace", "space"])
@pytest.mark.parametrize("n_char_order,n_word_order", [(6, 2), (6, 0), (4, 1), (2, 2)])
def test_chrf_option_sweep(n_char_order, n_word_order, whitespace):
    got = float(
        M.CHRFScore(
            n_char_order=n_char_order, n_word_order=n_word_order, whitespace=whitespace
        )(_PREDS, _TARGETS)
    )
    want = (
        SacreCHRF(char_order=n_char_order, word_order=n_word_order, whitespace=whitespace, eps_smoothing=True)
        .corpus_score(_PREDS, [[t[i] for t in _TARGETS] for i in range(2)])
        .score
        / 100.0
    )
    np.testing.assert_allclose(got, want, atol=1e-4)


@pytest.mark.parametrize(
    "flags",
    [
        {},
        {"normalize": True},
        {"no_punctuation": True},
        {"lowercase": False},
        {"normalize": True, "no_punctuation": True, "lowercase": True},
    ],
    ids=["default", "normalize", "nopunct", "cased", "all"],
)
def test_ter_option_sweep(flags):
    got = float(M.TranslationEditRate(**flags)(_PREDS, _TARGETS))
    want = (
        SacreTER(
            normalized=flags.get("normalize", False),
            no_punct=flags.get("no_punctuation", False),
            case_sensitive=not flags.get("lowercase", True),
        )
        .corpus_score(_PREDS, [[t[i] for t in _TARGETS] for i in range(2)])
        .score
        / 100.0
    )
    np.testing.assert_allclose(got, want, atol=1e-4)


@pytest.mark.parametrize("use_stemmer", [False, True], ids=["plain", "stemmer"])
@pytest.mark.parametrize("accumulate", ["best", "avg"])
def test_rouge_option_sweep(use_stemmer, accumulate):
    metric = M.ROUGEScore(use_stemmer=use_stemmer, accumulate=accumulate)
    got = metric(_PREDS, _TARGETS)

    from rouge_score.rouge_scorer import RougeScorer

    scorer = RougeScorer(["rouge1", "rouge2", "rougeL"], use_stemmer=use_stemmer)
    agg = {k: [] for k in ("rouge1", "rouge2", "rougeL")}
    for pred, refs in zip(_PREDS, _TARGETS):
        per_ref = [scorer.score(r, pred) for r in refs]
        if accumulate == "best":
            # reference semantics: ONE reference wins per sentence — the one
            # maximizing the FIRST key's fmeasure — and its scores are used
            # for every key (reference functional/text/rouge.py accumulate)
            best_idx = int(np.argmax([s["rouge1"].fmeasure for s in per_ref]))
            for key in agg:
                agg[key].append(per_ref[best_idx][key].fmeasure)
        else:
            for key in agg:
                agg[key].append(float(np.mean([s[key].fmeasure for s in per_ref])))
    for key in agg:
        np.testing.assert_allclose(
            float(got[f"{key}_fmeasure"]), float(np.mean(agg[key])), atol=1e-4, err_msg=key
        )


def test_degenerate_inputs_stay_finite():
    """Empty hypothesis / identical strings across every text metric."""
    preds = ["", "identical sentence"]
    flat_targets = ["some reference", "identical sentence"]
    nested_targets = [["some reference"], ["identical sentence"]]
    for cls, targets in [
        (M.WordErrorRate, flat_targets), (M.CharErrorRate, flat_targets),
        (M.MatchErrorRate, flat_targets), (M.WordInfoLost, flat_targets),
        (M.WordInfoPreserved, flat_targets), (M.BLEUScore, nested_targets),
        (M.SacreBLEUScore, nested_targets), (M.CHRFScore, nested_targets),
        (M.TranslationEditRate, nested_targets),
    ]:
        val = cls()(preds, targets)
        assert np.isfinite(float(val)), cls.__name__


# ---- EED cost-parameter sweep (reference text/eed.py:24 kwargs) ------------
# Cost monotonicity does NOT hold for EED (the optimal alignment path
# switches as costs change), so the sweep is pinned differentially against
# the reference implementation instead of against synthetic properties.
_EED_PREDS = ["this is the prediction", "here is an other sample", "fox"]
_EED_TARGET = ["this is the reference", "here is another one", "the quick brown fox jumps"]


def _reference_eed_fn():
    from tests.conftest import import_reference_torchmetrics

    import_reference_torchmetrics()
    from torchmetrics.functional.text.eed import extended_edit_distance as ref_eed

    return ref_eed


@pytest.mark.parametrize(
    "kwargs",
    [
        {},
        {"alpha": 3.0},
        {"alpha": 0.5},
        {"rho": 0.0},
        {"rho": 0.9},
        {"deletion": 1.0},
        {"insertion": 0.2},
        {"alpha": 4.0, "rho": 0.1, "deletion": 0.6, "insertion": 1.5},
    ],
    ids=lambda k: "-".join(f"{a}{v}" for a, v in k.items()) or "defaults",
)
def test_eed_param_grid_vs_reference(kwargs):
    """Every cost-parameter combination must match the reference EED exactly."""
    kwargs = {k: float(v) for k, v in kwargs.items()}
    ours = float(M.functional.extended_edit_distance(_EED_PREDS, _EED_TARGET, **kwargs))
    want = float(_reference_eed_fn()(_EED_PREDS, _EED_TARGET, **kwargs))
    np.testing.assert_allclose(ours, want, atol=1e-6)


def test_eed_sentence_level_scores_vs_reference():
    _, ours = M.functional.extended_edit_distance(_EED_PREDS, _EED_TARGET, return_sentence_level_score=True)
    _, want = _reference_eed_fn()(_EED_PREDS, _EED_TARGET, return_sentence_level_score=True)
    np.testing.assert_allclose(np.asarray(ours), np.asarray([float(w) for w in want]), atol=1e-6)


def test_eed_class_matches_functional_with_params():
    kwargs = dict(alpha=3.0, rho=0.2, deletion=0.5, insertion=0.8)
    m = M.ExtendedEditDistance(**kwargs)
    m.update(_EED_PREDS, _EED_TARGET)
    np.testing.assert_allclose(
        float(m.compute()),
        float(M.functional.extended_edit_distance(_EED_PREDS, _EED_TARGET, **kwargs)),
        atol=1e-7,
    )


@pytest.mark.parametrize("bad", [{"alpha": -1.0}, {"rho": -0.1}, {"deletion": -2.0}, {"insertion": -0.5}])
def test_eed_negative_params_raise(bad):
    with pytest.raises(ValueError):
        M.ExtendedEditDistance(**bad)
