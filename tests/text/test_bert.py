"""BERTScore tests with a deterministic toy encoder (no network access),
mirroring the reference's own-model example
(tm_examples/bert_score-own_model.py): user tokenizer + user_forward_fn.

Oracle: a plain numpy implementation of greedy cosine matching.
"""
import math

import numpy as np
import pytest

from metrics_tpu import BERTScore
from metrics_tpu.ops.text import bert_score

VOCAB = ["[CLS]", "[SEP]", "[PAD]", "hello", "there", "general", "kenobi", "master", "world", "hi"]
DIM = 16
MAX_LEN = 8

_rng = np.random.RandomState(0)
EMBED_TABLE = _rng.randn(len(VOCAB), DIM).astype(np.float32)


class ToyTokenizer:
    def __call__(self, sentences):
        ids = np.full((len(sentences), MAX_LEN), VOCAB.index("[PAD]"), dtype=np.int32)
        mask = np.zeros((len(sentences), MAX_LEN), dtype=np.int32)
        for row, sent in enumerate(sentences):
            tokens = ["[CLS]"] + sent.split()[: MAX_LEN - 2] + ["[SEP]"]
            for col, tok in enumerate(tokens):
                ids[row, col] = VOCAB.index(tok)
                mask[row, col] = 1
        return {"input_ids": ids, "attention_mask": mask}


def toy_forward_fn(model, batch):
    return EMBED_TABLE[np.asarray(batch["input_ids"])]


def _oracle_bertscore(preds, target, idf=False):
    tok = ToyTokenizer()
    p = tok(preds)
    t = tok(target)

    def sent_embs(ids, mask):
        out = []
        for row_ids, row_mask in zip(ids, mask):
            seq_len = int(row_mask.sum())
            content = row_ids[1 : seq_len - 1]  # drop CLS/SEP
            e = EMBED_TABLE[content]
            e = e / np.linalg.norm(e, axis=-1, keepdims=True)
            out.append((content, e))
        return out

    p_embs = sent_embs(p["input_ids"], p["attention_mask"])
    t_embs = sent_embs(t["input_ids"], t["attention_mask"])

    if idf:
        n = len(target)
        df = {}
        for row_ids, row_mask in zip(t["input_ids"], t["attention_mask"]):
            for i in set(row_ids[row_mask.astype(bool)].tolist()):
                df[i] = df.get(i, 0) + 1
        idf_map = lambda i: math.log((n + 1) / (df.get(i, 0) + 1))
    else:
        idf_map = lambda i: 1.0

    precisions, recalls, f1s = [], [], []
    for (p_ids, pe), (t_ids, te) in zip(p_embs, t_embs):
        sim = pe @ te.T
        pw = np.array([idf_map(i) for i in p_ids])
        tw = np.array([idf_map(i) for i in t_ids])
        prec = float((sim.max(axis=1) * (pw / pw.sum())).sum())
        rec = float((sim.max(axis=0) * (tw / tw.sum())).sum())
        f1 = 2 * prec * rec / (prec + rec) if prec + rec else 0.0
        precisions.append(prec)
        recalls.append(rec)
        f1s.append(f1)
    return {"precision": precisions, "recall": recalls, "f1": f1s}


PREDS = ["hello there", "master kenobi", "hello world"]
TARGET = ["hello there", "general kenobi", "hi world"]


@pytest.mark.parametrize("idf", [False, True])
def test_bert_score_vs_numpy_oracle(idf):
    got = bert_score(
        PREDS, TARGET, model="toy", user_tokenizer=ToyTokenizer(), user_forward_fn=toy_forward_fn, idf=idf
    )
    want = _oracle_bertscore(PREDS, TARGET, idf=idf)
    for key in ("precision", "recall", "f1"):
        np.testing.assert_allclose(got[key], want[key], atol=1e-5, err_msg=key)


def test_exact_match_scores_one():
    got = bert_score(
        ["hello there"], ["hello there"], model="toy", user_tokenizer=ToyTokenizer(), user_forward_fn=toy_forward_fn
    )
    np.testing.assert_allclose(got["f1"], [1.0], atol=1e-5)


def test_module_accumulates_batches():
    metric = BERTScore(model="toy", user_tokenizer=ToyTokenizer(), user_forward_fn=toy_forward_fn, max_length=MAX_LEN)
    metric.update(PREDS[:2], TARGET[:2])
    metric.update(PREDS[2:], TARGET[2:])
    got = metric.compute()
    want = _oracle_bertscore(PREDS, TARGET)
    for key in ("precision", "recall", "f1"):
        np.testing.assert_allclose(got[key], want[key], atol=1e-5, err_msg=key)


def test_return_hash():
    got = bert_score(
        ["hello there"], ["hello there"], model="toy", user_tokenizer=ToyTokenizer(),
        user_forward_fn=toy_forward_fn, return_hash=True,
    )
    assert "hash" in got


def test_mismatched_lengths_raise():
    with pytest.raises(ValueError):
        bert_score(["a", "b"], ["a"], model="toy", user_tokenizer=ToyTokenizer(), user_forward_fn=toy_forward_fn)


def test_bertscore_variable_width_tokenizer():
    """A user tokenizer padding each batch to its own longest sentence must
    still accumulate across updates (widths are right-padded at compute)."""

    class VarWidthTok:
        def __call__(self, sentences):
            width = max(len(s.split()) for s in sentences) + 2
            ids = np.full((len(sentences), width), VOCAB.index("[PAD]"), dtype=np.int32)
            mask = np.zeros((len(sentences), width), dtype=np.int32)
            for row, sent in enumerate(sentences):
                tokens = ["[CLS]"] + sent.split()[: width - 2] + ["[SEP]"]
                for col, tok in enumerate(tokens):
                    ids[row, col] = VOCAB.index(tok)
                    mask[row, col] = 1
            return {"input_ids": ids, "attention_mask": mask}

    preds = ["hello there", "general kenobi master hello world"]
    target = ["hello there", "master kenobi"]
    metric = BERTScore(model=object(), user_tokenizer=VarWidthTok(), user_forward_fn=toy_forward_fn, max_length=MAX_LEN)
    metric.update(preds[:1], target[:1])  # width 4
    metric.update(preds[1:], target[1:])  # width 7
    got = metric.compute()

    # same pairs through a fixed-width tokenizer in one update: the ragged
    # accumulation must be width-invariant (the oracle is not the yardstick
    # here — matching over padded widths floors negative cosines at 0, a
    # reference-parity behavior both paths share)
    fixed = BERTScore(model=object(), user_tokenizer=ToyTokenizer(), user_forward_fn=toy_forward_fn, max_length=MAX_LEN)
    fixed.update(preds, target)
    want = fixed.compute()
    np.testing.assert_allclose(np.asarray(got["f1"]), np.asarray(want["f1"]), rtol=1e-5)


def test_bertscore_packed_cache_parity_and_amortized_cost():
    """The pad-on-append packed buffers must (a) be byte-identical to the
    legacy ``_cat_padded`` full-history re-pad, and (b) do O(1) amortized
    copy work per update — the legacy path copied the whole history every
    compute, i.e. O(N²) over N updates."""

    class VarWidthTok:
        def __call__(self, sentences):
            width = max(len(s.split()) for s in sentences) + 2
            ids = np.full((len(sentences), width), VOCAB.index("[PAD]"), dtype=np.int32)
            mask = np.zeros((len(sentences), width), dtype=np.int32)
            for row, sent in enumerate(sentences):
                tokens = ["[CLS]"] + sent.split()[: width - 2] + ["[SEP]"]
                for col, tok in enumerate(tokens):
                    ids[row, col] = VOCAB.index(tok)
                    mask[row, col] = 1
            return {"input_ids": ids, "attention_mask": mask}

    metric = BERTScore(model=object(), user_tokenizer=VarWidthTok(), user_forward_fn=toy_forward_fn, max_length=MAX_LEN)
    sentences = ["hello there", "master kenobi hello", "hi world general kenobi master", "hello"]
    n_updates = 64
    for i in range(n_updates):
        s = sentences[i % len(sentences)]
        metric.update([s], [sentences[(i + 1) % len(sentences)]])

    packed = metric._packed_arrays()
    assert packed is not None, "packed mirrors should cover every update"
    for name in metric._STATE_NAMES:
        legacy = BERTScore._cat_padded(getattr(metric, name))
        assert packed[name].dtype == legacy.dtype and packed[name].shape == legacy.shape
        np.testing.assert_array_equal(np.asarray(packed[name]), legacy, err_msg=name)

    # Amortized O(1): total rows copied by reallocations stays linear in the
    # rows appended (geometric growth: < 2 copies/row/buffer across 4 buffers),
    # where the O(N²) re-pad would have copied ~N²/2 ≈ 2048 rows per buffer.
    rows = metric._packed["preds_input_ids"].rows
    assert rows == n_updates
    assert metric._packed_stats["rows_copied"] <= 2 * 4 * rows

    # byte-identical scores vs the forced fallback path
    got = metric.compute()
    metric._packed = {}
    want = metric.compute()
    for key in ("precision", "recall", "f1"):
        np.testing.assert_array_equal(np.asarray(got[key]), np.asarray(want[key]), err_msg=key)

    # invalidation: reset drops the mirrors; set_state falls back cleanly
    metric.reset()
    assert metric._packed == {} and metric.preds_input_ids == []
    metric.update(["hello"], ["hello"])
    assert metric._packed_arrays() is not None
    metric.set_state(metric.get_state())
    assert metric._packed_arrays() is None
    np.testing.assert_allclose(np.asarray(metric.compute()["f1"]), [1.0], atol=1e-5)


def test_bertscore_default_transformers_path(monkeypatch):
    """Gated end-to-end run of the default FlaxAutoModel path (verdict weak #5):
    executes when a transformers checkpoint is loadable (cached/local), skips
    in fully offline images."""
    import os

    if "HF_HUB_OFFLINE" not in os.environ:  # fail fast: cache-or-skip, no network retries
        monkeypatch.setenv("HF_HUB_OFFLINE", "1")
    transformers = pytest.importorskip("transformers")
    name = "sshleifer/tiny-distilroberta-base"
    try:
        transformers.AutoTokenizer.from_pretrained(name)
        transformers.FlaxAutoModel.from_pretrained(name)
    except Exception as err:  # no network / no cache
        pytest.skip(f"no loadable checkpoint offline: {err}")
    metric = BERTScore(model_name_or_path=name, max_length=16)
    metric.update(["hello world", "general kenobi"], ["hello there", "master kenobi"])
    out = metric.compute()
    assert len(out["f1"]) == 2
    assert all(np.isfinite(out["f1"]))
    # identical pair scores ~1 through a real encoder
    metric2 = BERTScore(model_name_or_path=name, max_length=16)
    metric2.update(["hello world"], ["hello world"])
    assert out and float(np.asarray(metric2.compute()["f1"])[0]) > 0.99
