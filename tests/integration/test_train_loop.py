"""Trainer-contract integration tests: metrics inside a real jax train loop.

Reference parity: integrations/test_lightning.py + integrations/lightning/
boring_model.py — the contract a trainer framework relies on: per-step
``forward`` logging, epoch-end ``compute`` parity with the concatenated
epoch data, ``reset`` between epochs, collections in the loop, and
checkpoint save/restore of metric state mid-epoch. The "trainer" here is a
plain optax SGD loop with the whole train step (model grad + metric update)
in ONE jitted XLA program — the TPU-native replacement for Lightning's
callback-driven loop.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from sklearn.metrics import accuracy_score

from metrics_tpu import Accuracy, F1Score, MeanMetric, MetricCollection, MeanSquaredError

_rng = np.random.default_rng(99)
N_CLASSES = 5
FEAT = 8
BATCH = 32
N_BATCHES = 6


def _data():
    w_true = _rng.normal(size=(FEAT, N_CLASSES))
    x = _rng.normal(size=(N_BATCHES, BATCH, FEAT)).astype(np.float32)
    y = np.argmax(x @ w_true + 0.5 * _rng.normal(size=(N_BATCHES, BATCH, N_CLASSES)), axis=-1).astype(np.int32)
    return jnp.asarray(x), jnp.asarray(y)


def test_metric_inside_jitted_train_step():
    """Model grad step + metric update compile to one XLA program; epoch-end
    compute matches sklearn on the epoch's predictions (reference
    test_lightning.py:30-61 epoch accumulation parity)."""
    x, y = _data()
    acc = Accuracy(num_classes=N_CLASSES)
    opt = optax.sgd(0.1)
    params = jnp.zeros((FEAT, N_CLASSES))
    opt_state = opt.init(params)

    @jax.jit
    def train_step(params, opt_state, metric_state, xb, yb):
        def loss_fn(p):
            logits = xb @ p
            return -jnp.mean(jnp.take_along_axis(jax.nn.log_softmax(logits), yb[:, None], axis=1)), logits

        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = opt.update(grads, opt_state)
        params = optax.apply_updates(params, updates)
        metric_state = acc.update_state(metric_state, logits, yb)
        return params, opt_state, metric_state, loss, logits

    metric_state = acc.init_state()
    all_logits = []
    for i in range(N_BATCHES):
        params, opt_state, metric_state, loss, logits = train_step(params, opt_state, metric_state, x[i], y[i])
        all_logits.append(np.asarray(logits))

    got = float(acc.compute_state(metric_state))
    preds = np.concatenate(all_logits).argmax(-1)
    want = accuracy_score(np.asarray(y).reshape(-1), preds)
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_epoch_accumulation_and_reset():
    """Stateful facade across epochs: per-step forward returns batch values,
    compute() the epoch value, reset() starts the next epoch clean
    (reference test_metrics_reset, integrations/test_lightning.py:64-178)."""
    x, y = _data()
    acc = Accuracy(num_classes=N_CLASSES)
    for epoch in range(2):
        batch_vals = []
        for i in range(N_BATCHES):
            logits = x[i] @ jnp.zeros((FEAT, N_CLASSES))  # untrained model
            batch_vals.append(float(acc(logits, y[i])))
        epoch_val = float(acc.compute())
        assert acc._update_count == N_BATCHES
        # epoch value is the pooled accuracy, not the mean of batch values
        np.testing.assert_allclose(
            epoch_val, accuracy_score(np.asarray(y).reshape(-1), np.zeros(N_BATCHES * BATCH)), atol=1e-6
        )
        acc.reset()
        assert acc._update_count == 0


def test_collection_logging_dict():
    """log_dict-style consumption of a MetricCollection inside the loop
    (reference test_metric_collection_lightning_log, :220-257)."""
    x, y = _data()
    coll = MetricCollection([Accuracy(num_classes=N_CLASSES), F1Score(num_classes=N_CLASSES, average="macro")])
    tracker = MeanMetric()
    for i in range(N_BATCHES):
        logits = x[i] @ jnp.zeros((FEAT, N_CLASSES))
        coll.update(logits, y[i])
        tracker.update(jnp.mean((logits.argmax(-1) == y[i]).astype(jnp.float32)))
    res = coll.compute()
    assert set(res) == {"Accuracy", "F1Score"}
    np.testing.assert_allclose(float(res["Accuracy"]), float(tracker.compute()), atol=1e-6)


def test_checkpoint_mid_epoch_resume():
    """Persistent metric state checkpoints mid-epoch and resumes exactly
    (reference tests/bases/test_ddp.py:135-241 save/restore semantics)."""
    x, y = _data()
    m1 = MeanSquaredError()
    m1.persistent(True)
    for i in range(3):
        m1.update(x[i].sum(-1), y[i].astype(jnp.float32))
    ckpt = m1.state_dict()

    m2 = MeanSquaredError()
    m2.load_state_dict(ckpt)
    m2._update_count = 3
    for i in range(3, N_BATCHES):
        m2.update(x[i].sum(-1), y[i].astype(jnp.float32))

    m_full = MeanSquaredError()
    for i in range(N_BATCHES):
        m_full.update(x[i].sum(-1), y[i].astype(jnp.float32))
    np.testing.assert_allclose(float(m2.compute()), float(m_full.compute()), rtol=1e-6)


def test_examples_run():
    """The examples/ directory doubles as API documentation (reference
    tm_examples/); each must execute end to end."""
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parents[2]
    env_path = f"{repo}"
    import os

    env = dict(os.environ)
    env["PYTHONPATH"] = env_path + (":" + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    for example in sorted((repo / "examples").glob("*.py")):
        # pin the subprocess to CPU like the conftest pins this process: a
        # config update, not env (sitecustomize preloads the TPU plugin, and a
        # wedged tunnel would hang the child at backend init)
        shim = (
            "import jax, runpy; jax.config.update('jax_platforms', 'cpu'); "
            f"runpy.run_path({str(example)!r}, run_name='__main__')"
        )
        proc = subprocess.run([sys.executable, "-c", shim], capture_output=True, env=env, timeout=600)
        assert proc.returncode == 0, f"{example.name} failed: {proc.stderr.decode()[-500:]}"
