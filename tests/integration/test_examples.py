"""Smoke-run the examples/ scripts — they double as API documentation
(reference parity: tm_examples/ scripts exercised as docs).

Each example runs as ``__main__`` in its own interpreter with the platform
forced to CPU *via the config* before any backend use (the container's
sitecustomize registers the accelerator platform before env vars can, see
tests/conftest.py).
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

EXAMPLES = [
    "compiled_scan_loop.py",
    "detection_map.py",
    "bert_score_own_model.py",
    "rouge_score_own_normalizer_and_tokenizer.py",
    "distributed_eval.py",
    "speech_quality_on_device.py",
]


@pytest.mark.parametrize("name", EXAMPLES)
def test_example_runs_clean(name):
    path = os.path.join(REPO, "examples", name)
    runner = (
        "import jax, runpy, sys; "
        "jax.config.update('jax_platforms', 'cpu'); "
        f"runpy.run_path({path!r}, run_name='__main__')"
    )
    env = dict(os.environ)
    env["XLA_FLAGS"] = env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    out = subprocess.run(
        [sys.executable, "-c", runner],
        capture_output=True,
        text=True,
        timeout=420,
        env=env,
        cwd=REPO,
    )
    assert out.returncode == 0, f"{name} failed:\n{out.stderr[-2000:]}"
