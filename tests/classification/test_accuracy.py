"""Accuracy parity vs sklearn (reference parity: tests/classification/test_accuracy.py)."""
import numpy as np
import pytest
from sklearn.metrics import accuracy_score as sk_accuracy

from metrics_tpu.classification import Accuracy
from metrics_tpu.ops.classification import accuracy
from tests.classification.inputs import (
    _input_binary,
    _input_binary_logits,
    _input_binary_prob,
    _input_multiclass,
    _input_multiclass_logits,
    _input_multiclass_prob,
    _input_multidim_multiclass,
    _input_multilabel_logits,
    _input_multilabel_multidim,
    _input_multilabel_no_match,
    _input_multilabel_prob,
)
from tests.helpers.testers import NUM_CLASSES, THRESHOLD, MetricTester


def _sk_accuracy(preds, target, subset_accuracy=False, **kw):
    """sklearn oracle re-using our canonicalization (reference test_accuracy.py:47-59)."""
    import jax.numpy as jnp

    from metrics_tpu.utils.checks import _input_format_classification

    sk_preds, sk_target, mode = _input_format_classification(jnp.asarray(preds), jnp.asarray(target), threshold=THRESHOLD)
    sk_preds, sk_target = np.asarray(sk_preds), np.asarray(sk_target)

    if mode == "multi-dim multi-class" and not subset_accuracy:
        sk_preds, sk_target = np.moveaxis(sk_preds, 1, -1).reshape(-1, sk_preds.shape[1]), np.moveaxis(
            sk_target, 1, -1
        ).reshape(-1, sk_target.shape[1])
    elif mode == "multi-label" and not subset_accuracy:
        sk_preds, sk_target = sk_preds.reshape(-1), sk_target.reshape(-1)
    elif mode == "multi-dim multi-class" and subset_accuracy:
        return np.mean([np.array_equal(p, t) for p, t in zip(sk_preds, sk_target)])
    return sk_accuracy(y_true=sk_target, y_pred=sk_preds)


@pytest.mark.parametrize(
    "preds, target, subset_accuracy, num_classes",
    [
        (_input_binary_prob.preds, _input_binary_prob.target, False, None),
        (_input_binary.preds, _input_binary.target, False, 2),
        (_input_binary_logits.preds, _input_binary_logits.target, False, None),
        (_input_multilabel_prob.preds, _input_multilabel_prob.target, False, None),
        (_input_multilabel_logits.preds, _input_multilabel_logits.target, False, None),
        # integer same-rank inputs classify as multi-dim multi-class, whose
        # one-hot lift needs a static num_classes (=2, binary labels) under jit
        (_input_multilabel_no_match.preds, _input_multilabel_no_match.target, False, 2),
        (_input_multilabel_multidim.preds, _input_multilabel_multidim.target, False, 2),
        (_input_multiclass_prob.preds, _input_multiclass_prob.target, False, None),
        (_input_multiclass_logits.preds, _input_multiclass_logits.target, False, None),
        (_input_multiclass.preds, _input_multiclass.target, False, NUM_CLASSES),
        (_input_multidim_multiclass.preds, _input_multidim_multiclass.target, False, NUM_CLASSES),
        (_input_multidim_multiclass.preds, _input_multidim_multiclass.target, True, NUM_CLASSES),
    ],
)
@pytest.mark.parametrize("ddp", [False, True])
class TestAccuracy(MetricTester):
    def test_accuracy_class(self, ddp, preds, target, subset_accuracy, num_classes):
        self.run_class_metric_test(
            ddp=ddp,
            preds=preds,
            target=target,
            metric_class=Accuracy,
            sk_metric=lambda p, t: _sk_accuracy(p, t, subset_accuracy),
            metric_args={"threshold": THRESHOLD, "subset_accuracy": subset_accuracy, "num_classes": num_classes},
        )

    def test_accuracy_fn(self, ddp, preds, target, subset_accuracy, num_classes):
        if ddp:
            pytest.skip("functional has no ddp")
        self.run_functional_metric_test(
            preds,
            target,
            metric_functional=lambda p, t: accuracy(
                p, t, threshold=THRESHOLD, subset_accuracy=subset_accuracy, num_classes=num_classes
            ),
            sk_metric=lambda p, t: _sk_accuracy(p, t, subset_accuracy),
        )


def test_accuracy_topk():
    """top-k accuracy vs hand-computed (reference test_accuracy.py top-k cases)."""
    import jax.numpy as jnp

    preds = jnp.asarray(
        [[0.35, 0.4, 0.25], [0.1, 0.5, 0.4], [0.2, 0.1, 0.7], [0.35, 0.4, 0.25], [0.1, 0.5, 0.4], [0.2, 0.1, 0.7]]
    )
    target = jnp.asarray([0, 0, 0, 1, 1, 1])
    assert float(accuracy(preds, target, top_k=2, num_classes=3)) == pytest.approx(4 / 6)


def test_accuracy_average_none_vs_sklearn():
    from sklearn.metrics import recall_score

    preds = _input_multiclass.preds[0]
    target = _input_multiclass.target[0]
    import jax.numpy as jnp

    res = accuracy(jnp.asarray(preds), jnp.asarray(target), average="macro", num_classes=NUM_CLASSES)
    sk = recall_score(target, preds, average="macro")  # class-accuracy == per-class recall
    np.testing.assert_allclose(np.asarray(res), sk, atol=1e-6)


def test_wrong_params():
    with pytest.raises(ValueError):
        Accuracy(average="bogus")
    with pytest.raises(ValueError):
        Accuracy(top_k=0)
