"""Shared numpy k-hot/one-hot canonicalization for independent test oracles.

Used by the option-product suites (`test_mdmc_product.py`,
`test_stat_scores_product.py`) so the from-scratch counting semantics live in
exactly one place — still with no code shared with the jax implementation.
"""
import numpy as np


def khot_rows(preds: np.ndarray, top_k, num_classes: int) -> np.ndarray:
    """(M,) hard labels or (M, C) probabilities -> (M, C) 0/1 k-hot matrix."""
    if preds.ndim == 1:
        out = np.zeros((preds.shape[0], num_classes), dtype=np.int64)
        out[np.arange(preds.shape[0]), preds] = 1
        return out
    k = top_k or 1
    top = np.argsort(-preds, axis=-1, kind="stable")[:, :k]
    out = np.zeros_like(preds, dtype=np.int64)
    np.put_along_axis(out, top, 1, axis=-1)
    return out


def onehot_rows(target: np.ndarray, num_classes: int) -> np.ndarray:
    out = np.zeros((target.shape[0], num_classes), dtype=np.int64)
    out[np.arange(target.shape[0]), target] = 1
    return out
