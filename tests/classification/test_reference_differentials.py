"""Classification option surfaces pinned directly against the reference.

Where the repo's other tests use self-written numpy oracles, this module
removes the self-oracle risk by asserting exact agreement with the
reference running live on the same inputs: CalibrationError norm × n_bins,
HingeLoss squared × multiclass_mode, F1/Accuracy mdmc cells, JaccardIndex
ignore_index/absent_score, CohenKappa weights, Dice average × top_k ×
ignore_index (reference functional/classification/*.py). Uses the shared
conftest import helper; skips when the checkout or torch is unavailable.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.ops.classification import calibration_error, hinge_loss
from metrics_tpu.functional import (
    accuracy as mt_accuracy,
    cohen_kappa as mt_cohen_kappa,
    dice as mt_dice,
    f1_score as mt_f1_score,
    jaccard_index as mt_jaccard_index,
    precision_recall_curve as mt_prc,
    roc as mt_roc,
)
from tests.classification.inputs import _input_binary_prob, _input_multiclass_prob


def _ref():
    from tests.conftest import reference_functional

    return reference_functional()


@pytest.mark.parametrize("n_bins", [5, 15, 30])
@pytest.mark.parametrize("norm", ["l1", "l2", "max"])
def test_calibration_binary_vs_reference(norm, n_bins):
    torch, F = _ref()
    preds, target = _input_binary_prob.preds[0], _input_binary_prob.target[0]
    ours = float(calibration_error(jnp.asarray(preds), jnp.asarray(target), norm=norm, n_bins=n_bins))
    want = float(
        F.calibration_error(torch.tensor(preds), torch.tensor(target), norm=norm, n_bins=n_bins)
    )
    np.testing.assert_allclose(ours, want, atol=1e-6)


@pytest.mark.parametrize("norm", ["l1", "l2", "max"])
def test_calibration_multiclass_vs_reference(norm):
    torch, F = _ref()
    preds, target = _input_multiclass_prob.preds[0], _input_multiclass_prob.target[0]
    ours = float(calibration_error(jnp.asarray(preds), jnp.asarray(target), norm=norm))
    want = float(F.calibration_error(torch.tensor(preds), torch.tensor(target), norm=norm))
    np.testing.assert_allclose(ours, want, atol=1e-6)


@pytest.mark.parametrize("squared", [False, True], ids=["hinge", "squared"])
def test_hinge_binary_vs_reference(squared):
    torch, F = _ref()
    rng = np.random.default_rng(8)
    preds = rng.standard_normal(64).astype(np.float32)
    target = rng.integers(0, 2, 64)
    ours = float(hinge_loss(jnp.asarray(preds), jnp.asarray(target), squared=squared))
    want = float(F.hinge_loss(torch.tensor(preds), torch.tensor(target), squared=squared))
    np.testing.assert_allclose(ours, want, atol=1e-6)


@pytest.mark.parametrize("mode", ["crammer-singer", "one-vs-all"])
@pytest.mark.parametrize("squared", [False, True], ids=["hinge", "squared"])
def test_hinge_multiclass_vs_reference(squared, mode):
    torch, F = _ref()
    rng = np.random.default_rng(9)
    preds = rng.standard_normal((64, 4)).astype(np.float32)
    target = rng.integers(0, 4, 64)
    ours = hinge_loss(jnp.asarray(preds), jnp.asarray(target), squared=squared, multiclass_mode=mode)
    want = F.hinge_loss(torch.tensor(preds), torch.tensor(target), squared=squared, multiclass_mode=mode)
    np.testing.assert_allclose(np.asarray(ours), np.asarray(want), atol=1e-6)


@pytest.mark.parametrize("ignore_index", [None, 1])
@pytest.mark.parametrize("top_k", [None, 2])
@pytest.mark.parametrize("mdmc_average", ["global", "samplewise"])
@pytest.mark.parametrize("average", ["micro", "macro", "weighted"])
def test_f1_mdmc_cells_vs_reference(average, mdmc_average, top_k, ignore_index):
    """Cross-validates the repo's numpy k-hot oracle: the same option cells
    the mdmc product asserts against numpy must also match the reference."""
    torch, F = _ref()
    rng = np.random.default_rng(12)
    preds = rng.dirichlet(np.ones(4), (32, 6)).astype(np.float32).transpose(0, 2, 1)  # (N, C, X)
    target = rng.integers(0, 4, (32, 6))
    kwargs = dict(
        average=average, mdmc_average=mdmc_average, num_classes=4, top_k=top_k, ignore_index=ignore_index
    )
    ours = float(
        mt_f1_score(jnp.asarray(preds), jnp.asarray(target), **kwargs)
    )
    want = float(F.f1_score(torch.tensor(preds), torch.tensor(target), **kwargs))
    np.testing.assert_allclose(ours, want, atol=1e-6)


@pytest.mark.parametrize("subset_accuracy", [False, True], ids=["plain", "subset"])
@pytest.mark.parametrize("mdmc_average", ["global", "samplewise"])
def test_accuracy_mdmc_cells_vs_reference(mdmc_average, subset_accuracy):
    torch, F = _ref()
    rng = np.random.default_rng(13)
    preds = rng.dirichlet(np.ones(4), (32, 6)).astype(np.float32).transpose(0, 2, 1)
    target = rng.integers(0, 4, (32, 6))
    kwargs = dict(mdmc_average=mdmc_average, num_classes=4, subset_accuracy=subset_accuracy)
    ours = float(
        mt_accuracy(jnp.asarray(preds), jnp.asarray(target), **kwargs)
    )
    want = float(F.accuracy(torch.tensor(preds), torch.tensor(target), **kwargs))
    np.testing.assert_allclose(ours, want, atol=1e-6)


@pytest.mark.parametrize("absent_score", [0.0, 1.0, -1.0])
@pytest.mark.parametrize("ignore_index", [None, 0])
@pytest.mark.parametrize("average", ["macro", "none"])
def test_jaccard_options_vs_reference(average, ignore_index, absent_score):
    """ignore_index/absent_score/average surface of JaccardIndex — the
    repo's other jaccard tests only sweep average vs sklearn."""
    torch, F = _ref()
    rng = np.random.default_rng(14)
    # class 3 absent from BOTH arrays -> union == 0 -> absent_score applies
    preds = rng.integers(0, 3, 64)
    target = rng.integers(0, 3, 64)
    kwargs = dict(num_classes=4, average=average, ignore_index=ignore_index, absent_score=absent_score)
    ours = np.asarray(
        mt_jaccard_index(jnp.asarray(preds), jnp.asarray(target), **kwargs)
    )
    want = np.asarray(F.jaccard_index(torch.tensor(preds), torch.tensor(target), **kwargs))
    np.testing.assert_allclose(ours, want, atol=1e-6)


@pytest.mark.parametrize("weights", [None, "linear", "quadratic"])
def test_cohen_kappa_weights_vs_reference(weights):
    torch, F = _ref()
    rng = np.random.default_rng(15)
    preds = rng.integers(0, 5, 128)
    target = rng.integers(0, 5, 128)
    ours = float(
        mt_cohen_kappa(
            jnp.asarray(preds), jnp.asarray(target), num_classes=5, weights=weights
        )
    )
    want = float(F.cohen_kappa(torch.tensor(preds), torch.tensor(target), num_classes=5, weights=weights))
    np.testing.assert_allclose(ours, want, atol=1e-6)


@pytest.mark.parametrize("ignore_index", [None, 1])
@pytest.mark.parametrize("top_k", [None, 2])
@pytest.mark.parametrize("average", ["micro", "macro", "samples"])
def test_dice_options_vs_reference(average, top_k, ignore_index):
    torch, F = _ref()
    rng = np.random.default_rng(16)
    preds = rng.dirichlet(np.ones(4), 48).astype(np.float32)
    target = rng.integers(0, 4, 48)
    kwargs = dict(average=average, num_classes=4, top_k=top_k, ignore_index=ignore_index)
    ours = float(mt_dice(jnp.asarray(preds), jnp.asarray(target), **kwargs))
    want = float(F.dice(torch.tensor(preds), torch.tensor(target), **kwargs))
    np.testing.assert_allclose(ours, want, atol=1e-6)


def test_roc_prc_output_format_vs_reference():
    """Curve OUTPUT CONTRACT: the reference prepends a max+1 threshold to ROC
    and returns per-class lists for multiclass — both pinned exactly."""
    torch, F = _ref()
    p = np.asarray([0.1, 0.4, 0.35, 0.8], np.float32)
    t = np.asarray([0, 0, 1, 1])
    ours_roc = mt_roc(jnp.asarray(p), jnp.asarray(t), pos_label=1)
    want_roc = F.roc(torch.tensor(p), torch.tensor(t), pos_label=1)
    assert len(ours_roc) == len(want_roc) == 3  # (fpr, tpr, thresholds)
    for ours, want in zip(ours_roc, want_roc):
        np.testing.assert_allclose(np.asarray(ours), np.asarray(want), atol=1e-6)
    ours_prc = mt_prc(jnp.asarray(p), jnp.asarray(t), pos_label=1)
    want_prc = F.precision_recall_curve(torch.tensor(p), torch.tensor(t), pos_label=1)
    assert len(ours_prc) == len(want_prc) == 3  # (precision, recall, thresholds)
    for ours, want in zip(ours_prc, want_prc):
        np.testing.assert_allclose(np.asarray(ours), np.asarray(want), atol=1e-6)

    # multiclass: list-of-arrays per class on both sides
    rng = np.random.default_rng(17)
    pm = rng.dirichlet(np.ones(3), 32).astype(np.float32)
    tm_ = rng.integers(0, 3, 32)
    ours_l = mt_roc(jnp.asarray(pm), jnp.asarray(tm_), num_classes=3)
    want_l = F.roc(torch.tensor(pm), torch.tensor(tm_), num_classes=3)
    assert len(ours_l) == len(want_l) == 3
    for ours_part, want_part in zip(ours_l, want_l):
        assert len(ours_part) == len(want_part) == 3
        for o, w in zip(ours_part, want_part):
            np.testing.assert_allclose(np.asarray(o), np.asarray(w), atol=1e-6)
