"""Stat-scores-family parity over the FULL input-type zoo.

The Accuracy suite runs every fixture input type; this extends the same
treatment to the shared StatScores engine and the Precision/Recall/F1 family
(reference parity: tests/classification/test_stat_scores.py +
test_precision_recall.py's full `pytest.mark.parametrize` input grid built on
tests/classification/inputs.py:25-80).

Oracle strategy: reuse the library's own canonicalization (as the reference's
sk-wrappers do) to lift every input type to multilabel-indicator ``(N, C)``
arrays, then score with sklearn's indicator-format metrics.
"""
import numpy as np
import pytest
from sklearn.metrics import fbeta_score as sk_fbeta
from sklearn.metrics import precision_score as sk_precision
from sklearn.metrics import recall_score as sk_recall

from metrics_tpu.classification import F1Score, Precision, Recall, StatScores
from tests.classification.inputs import (
    _input_binary,
    _input_binary_logits,
    _input_binary_prob,
    _input_multiclass,
    _input_multiclass_logits,
    _input_multiclass_prob,
    _input_multidim_multiclass,
    _input_multidim_multiclass_prob,
    _input_multilabel,
    _input_multilabel_logits,
    _input_multilabel_prob,
)
from tests.helpers.testers import NUM_CLASSES, THRESHOLD, MetricTester

# (name, fixture, num_classes) — binary counts one class; int same-rank
# multilabel inputs classify as multi-dim multi-class with 2 classes
# (reference checks.py mode table), so they need a static num_classes
ZOO = [
    ("binary_prob", _input_binary_prob, 1),
    ("binary", _input_binary, None),  # num_classes=1 + int preds is ambiguous by design
    ("binary_logits", _input_binary_logits, 1),
    ("multilabel_prob", _input_multilabel_prob, NUM_CLASSES),
    ("multilabel", _input_multilabel, 2),
    ("multilabel_logits", _input_multilabel_logits, NUM_CLASSES),
    ("multiclass_prob", _input_multiclass_prob, NUM_CLASSES),
    ("multiclass", _input_multiclass, NUM_CLASSES),
    ("multiclass_logits", _input_multiclass_logits, NUM_CLASSES),
    ("multidim_multiclass_prob", _input_multidim_multiclass_prob, NUM_CLASSES),
    ("multidim_multiclass", _input_multidim_multiclass, NUM_CLASSES),
]


def _canonical(preds, target):
    """(N, C) indicator arrays via the library's own input machine."""
    import jax.numpy as jnp

    from metrics_tpu.utils.checks import _input_format_classification

    c_preds, c_target, _ = _input_format_classification(
        jnp.asarray(preds), jnp.asarray(target), threshold=THRESHOLD
    )
    c_preds, c_target = np.asarray(c_preds), np.asarray(c_target)
    if c_preds.ndim == 3:  # (N, C, X): fold the extra dim (mdmc 'global')
        c_preds = np.moveaxis(c_preds, 1, -1).reshape(-1, c_preds.shape[1])
        c_target = np.moveaxis(c_target, 1, -1).reshape(-1, c_target.shape[1])
    return c_preds, c_target


def _sk_indicator(sk_fn, preds, target, average, **kw):
    c_preds, c_target = _canonical(preds, target)
    if c_preds.shape[1] == 1:
        # sklearn squeezes (N, 1) indicators to 1D labels (micro would become
        # accuracy); binary-mode metrics count the positive class only
        return sk_fn(c_target.ravel(), c_preds.ravel(), average="binary", zero_division=0, **kw)
    return sk_fn(c_target, c_preds, average=average, zero_division=0, **kw)


def _sk_stat_scores_micro(preds, target):
    """[tp, fp, tn, fn, support] totals from the canonical indicator arrays."""
    c_preds, c_target = _canonical(preds, target)
    tp = int(((c_preds == 1) & (c_target == 1)).sum())
    fp = int(((c_preds == 1) & (c_target == 0)).sum())
    tn = int(((c_preds == 0) & (c_target == 0)).sum())
    fn = int(((c_preds == 0) & (c_target == 1)).sum())
    return np.asarray([tp, fp, tn, fn, tp + fn])


@pytest.mark.parametrize("case,inputs,num_classes", ZOO, ids=[z[0] for z in ZOO])
class TestStatScoresZoo(MetricTester):
    def test_stat_scores_micro(self, case, inputs, num_classes):
        self.run_class_metric_test(
            ddp=False,
            preds=inputs.preds,
            target=inputs.target,
            metric_class=StatScores,
            sk_metric=_sk_stat_scores_micro,
            metric_args={"reduce": "micro", "mdmc_reduce": "global", "threshold": THRESHOLD, "num_classes": num_classes},
        )


def _prf_args(case, num_classes, average):
    if case == "binary" and average == "macro":
        # int-binary macro needs multiclass=False + num_classes=1, a combination
        # whose class folding is deliberately ambiguous — not part of the grid
        # (the reference's binary fixtures run the default average only)
        pytest.skip("int-binary macro is an ambiguous configuration")
    return {"average": average, "mdmc_average": "global", "threshold": THRESHOLD, "num_classes": num_classes}


@pytest.mark.parametrize("average", ["micro", "macro"])
@pytest.mark.parametrize("case,inputs,num_classes", ZOO, ids=[z[0] for z in ZOO])
class TestPRFZoo(MetricTester):
    def test_precision_zoo(self, case, inputs, num_classes, average):
        self.run_class_metric_test(
            ddp=False,
            preds=inputs.preds,
            target=inputs.target,
            metric_class=Precision,
            sk_metric=lambda p, t: _sk_indicator(sk_precision, p, t, average),
            metric_args=_prf_args(case, num_classes, average),
        )

    def test_recall_zoo(self, case, inputs, num_classes, average):
        self.run_class_metric_test(
            ddp=False,
            preds=inputs.preds,
            target=inputs.target,
            metric_class=Recall,
            sk_metric=lambda p, t: _sk_indicator(sk_recall, p, t, average),
            metric_args=_prf_args(case, num_classes, average),
        )

    def test_f1_zoo(self, case, inputs, num_classes, average):
        self.run_class_metric_test(
            ddp=False,
            preds=inputs.preds,
            target=inputs.target,
            metric_class=F1Score,
            sk_metric=lambda p, t: _sk_indicator(lambda y, yp, **k: sk_fbeta(y, yp, beta=1.0, **k), p, t, average),
            metric_args=_prf_args(case, num_classes, average),
        )


@pytest.mark.parametrize("case,inputs,num_classes", [ZOO[0], ZOO[7]], ids=["binary_prob", "multiclass"])
def test_prf_zoo_ddp_smoke(case, inputs, num_classes):
    """One binary and one multiclass case through the real collective path."""
    MetricTester().run_class_metric_test(
        ddp=True,
        preds=inputs.preds,
        target=inputs.target,
        metric_class=Precision,
        sk_metric=lambda p, t: _sk_indicator(sk_precision, p, t, "micro"),
        metric_args={"average": "micro", "mdmc_average": "global", "threshold": THRESHOLD, "num_classes": num_classes},
    )
