"""Pallas binned-counting kernel vs the XLA broadcast, in interpret mode.

The kernel itself targets TPU (ops/classification/binned_pallas.py); on the
CPU CI mesh it runs under the pallas interpreter, which validates the exact
same kernel program the TPU lowers.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.ops.classification.binned_pallas import (
    _BLOCK_N,
    _binned_counts_broadcast,
    _binned_counts_xla,
    binned_stat_counts,
)

_rng = np.random.default_rng(41)


@pytest.mark.parametrize(
    "n,c,t",
    [(64, 3, 11), (300, 1, 100), (513, 5, 50), (7, 2, 1)],
)
def test_bucketized_matches_broadcast(n, c, t):
    """The O(N*C + C*T) bucketize path == the naive broadcast, exactly."""
    preds = jnp.asarray(_rng.uniform(size=(n, c)).astype(np.float32))
    target = jnp.asarray(_rng.integers(0, 2, size=(n, c)).astype(bool))
    thresholds = jnp.linspace(0.0, 1.0, t)
    got = _binned_counts_xla(preds, target, thresholds)
    want = _binned_counts_broadcast(preds, target, thresholds)
    for g, w, name in zip(got, want, ("TP", "FP", "FN")):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w), err_msg=name)


def test_bucketized_nan_preds_match_broadcast():
    """NaN scores are predicted-negative at every threshold on all paths."""
    preds = jnp.asarray([[jnp.nan], [0.7], [0.2]], dtype=jnp.float32)
    target = jnp.asarray([[1], [1], [0]]).astype(bool)
    thresholds = jnp.linspace(0.0, 1.0, 5)
    got = _binned_counts_xla(preds, target, thresholds)
    want = _binned_counts_broadcast(preds, target, thresholds)
    for g, w, name in zip(got, want, ("TP", "FP", "FN")):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w), err_msg=name)


def test_bucketized_unsorted_and_tied_thresholds():
    """User threshold grids need not be sorted; scores may sit ON thresholds."""
    preds = jnp.asarray([[0.0], [0.5], [0.5], [1.0], [0.25]], dtype=jnp.float32)
    target = jnp.asarray([[1], [1], [0], [1], [0]]).astype(bool)
    thresholds = jnp.asarray([0.5, 0.0, 1.0, 0.5, 0.25])  # unsorted + duplicate 0.5
    got = _binned_counts_xla(preds, target, thresholds)
    want = _binned_counts_broadcast(preds, target, thresholds)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))


@pytest.mark.parametrize(
    "n,c,t",
    [
        (64, 3, 11),  # n < block (pure padding path)
        (_BLOCK_N, 4, 21),  # exactly one block
        (2 * _BLOCK_N + 17, 5, 50),  # multi-block + ragged tail
        (300, 1, 100),  # single class
    ],
)
def test_pallas_counts_match_xla(n, c, t):
    preds = jnp.asarray(_rng.uniform(size=(n, c)).astype(np.float32))
    target = jnp.asarray(_rng.integers(0, 2, size=(n, c)).astype(bool))
    thresholds = jnp.linspace(0.0, 1.0, t)
    got = binned_stat_counts(preds, target, thresholds, use_pallas="force")
    want = _binned_counts_xla(preds, target, thresholds)
    for g, w, name in zip(got, want, ("TP", "FP", "FN")):
        assert g.shape == (c, t)
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w), err_msg=name)


def test_pallas_counts_boundary_thresholds():
    # scores exactly on a threshold must count as predicted-positive (>=)
    preds = jnp.asarray([[0.0], [0.5], [1.0]], dtype=jnp.float32)
    target = jnp.asarray([[True], [False], [True]])
    thresholds = jnp.asarray([0.0, 0.5, 1.0])
    got = binned_stat_counts(preds, target, thresholds, use_pallas="force")
    want = _binned_counts_xla(preds, target, thresholds)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))


def test_binned_curve_metric_uses_kernel(monkeypatch):
    # end to end through BinnedPrecisionRecallCurve with the kernel forced on
    monkeypatch.setenv("METRICS_TPU_PALLAS", "1")
    from metrics_tpu import BinnedPrecisionRecallCurve

    n, c = 140, 3
    preds = jnp.asarray(_rng.uniform(size=(n, c)).astype(np.float32))
    target = jnp.asarray(_rng.integers(0, 2, size=(n, c)).astype(np.int32))
    m_pallas = BinnedPrecisionRecallCurve(num_classes=c, thresholds=25)
    m_pallas.update(preds, target)
    monkeypatch.delenv("METRICS_TPU_PALLAS")
    m_xla = BinnedPrecisionRecallCurve(num_classes=c, thresholds=25)
    m_xla.update(preds, target)
    for a, b in zip(m_pallas.compute(), m_xla.compute()):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_xla_impl_flag_selects_formulation(monkeypatch):
    """`xla_impl` / METRICS_TPU_BINNED_XLA pick the XLA formulation: scatter
    (default) and broadcast must agree exactly; bad values must raise."""
    preds = jnp.asarray(_rng.uniform(size=(90, 3)).astype(np.float32))
    target = jnp.asarray(_rng.integers(0, 2, size=(90, 3)).astype(bool))
    thresholds = jnp.linspace(0.0, 1.0, 13)
    default = binned_stat_counts(preds, target, thresholds, use_pallas="never")
    scatter = binned_stat_counts(preds, target, thresholds, use_pallas="never", xla_impl="scatter")
    broadcast = binned_stat_counts(preds, target, thresholds, use_pallas="never", xla_impl="broadcast")
    for d, s, b in zip(default, scatter, broadcast):
        np.testing.assert_array_equal(np.asarray(d), np.asarray(s))
        np.testing.assert_array_equal(np.asarray(d), np.asarray(b))
    # the env var overrides the argument process-wide
    monkeypatch.setenv("METRICS_TPU_BINNED_XLA", "broadcast")
    env_forced = binned_stat_counts(preds, target, thresholds, use_pallas="never")
    for d, e in zip(default, env_forced):
        np.testing.assert_array_equal(np.asarray(d), np.asarray(e))
    monkeypatch.setenv("METRICS_TPU_BINNED_XLA", "bogus")
    with pytest.raises(ValueError, match="xla_impl"):
        binned_stat_counts(preds, target, thresholds, use_pallas="never")


def test_empty_batch_returns_zeros():
    got = binned_stat_counts(
        jnp.zeros((0, 3)), jnp.zeros((0, 3), bool), jnp.linspace(0, 1, 5), use_pallas="force"
    )
    for g in got:
        np.testing.assert_array_equal(np.asarray(g), np.zeros((3, 5)))


def test_out_of_range_thresholds_padding_safe():
    # thresholds below 0: padded -inf rows must not count as predictions
    preds = jnp.asarray(_rng.uniform(size=(100, 2)).astype(np.float32))
    target = jnp.asarray(_rng.integers(0, 2, size=(100, 2)).astype(bool))
    thresholds = jnp.asarray([-2.0, 0.5, 3.0])
    got = binned_stat_counts(preds, target, thresholds, use_pallas="force")
    want = _binned_counts_xla(preds, target, thresholds)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))
