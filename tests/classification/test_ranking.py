"""Multilabel ranking metrics vs the exact sklearn oracles.

Reference analog: tests/classification/test_ranking.py runs CoverageError /
LabelRankingAveragePrecision / LabelRankingLoss against
sklearn.metrics.{coverage_error, label_ranking_average_precision_score,
label_ranking_loss} over the multilabel fixtures × ddp × sample_weight.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import (
    coverage_error as sk_coverage,
    label_ranking_average_precision_score as sk_lrap,
    label_ranking_loss as sk_lrl,
)

from metrics_tpu import CoverageError, LabelRankingAveragePrecision, LabelRankingLoss
from metrics_tpu.functional import coverage_error, label_ranking_average_precision, label_ranking_loss
from tests.helpers.testers import merge_world

NB, BS, C = 4, 16, 6
_rng = np.random.default_rng(99)
_preds = _rng.random((NB, BS, C)).astype(np.float32)
_target = _rng.integers(0, 2, (NB, BS, C))
# every sample needs >=1 positive and >=1 negative for all three oracles
_target[:, :, 0] = 1
_target[:, :, 1] = 0

CASES = [
    (CoverageError, coverage_error, lambda t, p, w=None: sk_coverage(t, p, sample_weight=w)),
    (LabelRankingAveragePrecision, label_ranking_average_precision, lambda t, p, w=None: sk_lrap(t, p, sample_weight=w)),
    (LabelRankingLoss, label_ranking_loss, lambda t, p, w=None: sk_lrl(t, p, sample_weight=w)),
]
IDS = ["coverage", "lrap", "ranking_loss"]


@pytest.mark.parametrize("metric_cls,fn,sk", CASES, ids=IDS)
def test_functional_parity(metric_cls, fn, sk):
    p, t = _preds.reshape(-1, C), _target.reshape(-1, C)
    np.testing.assert_allclose(float(fn(jnp.asarray(p), jnp.asarray(t))), sk(t, p), atol=1e-5)


@pytest.mark.parametrize("metric_cls,fn,sk", CASES, ids=IDS)
def test_class_accumulation(metric_cls, fn, sk):
    """Batched updates == sklearn on the concatenated stream (the states are
    sample-sums, so accumulation must be exact)."""
    m = metric_cls()
    for i in range(NB):
        m.update(jnp.asarray(_preds[i]), jnp.asarray(_target[i]))
    p, t = _preds.reshape(-1, C), _target.reshape(-1, C)
    np.testing.assert_allclose(float(m.compute()), sk(t, p), atol=1e-5)


@pytest.mark.parametrize("metric_cls,fn,sk", CASES, ids=IDS)
def test_sample_weight(metric_cls, fn, sk):
    # fresh seeded rng: each parametrized cell draws the same weights in
    # isolation as in the full suite
    w = np.random.default_rng(7).random(NB * BS).astype(np.float32) + 0.1
    p, t = _preds.reshape(-1, C), _target.reshape(-1, C)
    m = metric_cls()
    half = (NB * BS) // 2
    m.update(jnp.asarray(p[:half]), jnp.asarray(t[:half]), sample_weight=jnp.asarray(w[:half]))
    m.update(jnp.asarray(p[half:]), jnp.asarray(t[half:]), sample_weight=jnp.asarray(w[half:]))
    np.testing.assert_allclose(float(m.compute()), sk(t, p, w), atol=1e-5)


@pytest.mark.parametrize("metric_cls,fn,sk", CASES, ids=IDS)
def test_ddp_world_merge(metric_cls, fn, sk):
    ranks = []
    for r in range(4):
        m = metric_cls()
        m.update(jnp.asarray(_preds.reshape(-1, C)[r::4]), jnp.asarray(_target.reshape(-1, C)[r::4]))
        ranks.append(m)
    p, t = _preds.reshape(-1, C), _target.reshape(-1, C)
    np.testing.assert_allclose(float(merge_world(ranks).compute()), sk(t, p), atol=1e-5)


@pytest.mark.parametrize("metric_cls,fn,sk", CASES, ids=IDS)
def test_update_jits(metric_cls, fn, sk):
    """Sum-state ranking updates are static-shape: the pure update must jit."""
    m = metric_cls()
    state = jax.jit(m.update_state)(m.init_state(), jnp.asarray(_preds[0]), jnp.asarray(_target[0]))
    got = float(m.compute_state(state))
    np.testing.assert_allclose(got, sk(_target[0], _preds[0]), atol=1e-5)
