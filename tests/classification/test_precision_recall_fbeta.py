"""Precision/Recall/F-beta/Specificity/Dice parity vs sklearn.

Reference parity: tests/classification/test_precision_recall.py + test_f_beta.py
+ test_specificity.py + test_dice.py (compacted grid).
"""
import numpy as np
import pytest
from sklearn.metrics import fbeta_score as sk_fbeta
from sklearn.metrics import precision_score as sk_precision
from sklearn.metrics import recall_score as sk_recall

from metrics_tpu.classification import F1Score, FBetaScore, Precision, Recall, Specificity
from metrics_tpu.ops.classification import f1_score, fbeta_score, precision, recall, specificity
from tests.classification.inputs import _input_multiclass, _input_multiclass_prob, _input_multilabel_prob
from tests.helpers.testers import NUM_CLASSES, THRESHOLD, MetricTester


def _sk_prf(sk_fn, preds, target, average, input_type, **fn_kwargs):
    if input_type == "mc_prob":
        preds = np.argmax(preds, axis=-1)
    elif input_type == "ml_prob":
        preds = (preds >= THRESHOLD).astype(int)
        target = target.reshape(-1, target.shape[-1])
        preds = preds.reshape(-1, preds.shape[-1])
    return sk_fn(target, preds, average=average, zero_division=0, **fn_kwargs)


_CASES = [
    ("mc", _input_multiclass.preds, _input_multiclass.target),
    ("mc_prob", _input_multiclass_prob.preds, _input_multiclass_prob.target),
    ("ml_prob", _input_multilabel_prob.preds, _input_multilabel_prob.target),
]


@pytest.mark.parametrize("average", ["micro", "macro", "weighted", None])
@pytest.mark.parametrize("case,preds,target", _CASES)
@pytest.mark.parametrize("ddp", [False, True])
class TestPrecisionRecall(MetricTester):
    def test_precision(self, ddp, case, preds, target, average):
        self.run_class_metric_test(
            ddp=ddp,
            preds=preds,
            target=target,
            metric_class=Precision,
            sk_metric=lambda p, t: _sk_prf(sk_precision, p, t, average, case),
            metric_args={"average": average, "num_classes": NUM_CLASSES, "threshold": THRESHOLD},
        )

    def test_recall(self, ddp, case, preds, target, average):
        self.run_class_metric_test(
            ddp=ddp,
            preds=preds,
            target=target,
            metric_class=Recall,
            sk_metric=lambda p, t: _sk_prf(sk_recall, p, t, average, case),
            metric_args={"average": average, "num_classes": NUM_CLASSES, "threshold": THRESHOLD},
        )

    def test_f1(self, ddp, case, preds, target, average):
        self.run_class_metric_test(
            ddp=ddp,
            preds=preds,
            target=target,
            metric_class=F1Score,
            sk_metric=lambda p, t: _sk_prf(lambda y, yp, **k: sk_fbeta(y, yp, beta=1.0, **k), p, t, average, case),
            metric_args={"average": average, "num_classes": NUM_CLASSES, "threshold": THRESHOLD},
        )


@pytest.mark.parametrize("beta", [0.5, 2.0])
def test_fbeta_functional(beta):
    import jax.numpy as jnp

    preds, target = _input_multiclass.preds[0], _input_multiclass.target[0]
    res = fbeta_score(jnp.asarray(preds), jnp.asarray(target), beta=beta, average="macro", num_classes=NUM_CLASSES)
    sk = sk_fbeta(target, preds, beta=beta, average="macro", zero_division=0)
    np.testing.assert_allclose(np.asarray(res), sk, atol=1e-6)


def test_specificity_vs_recall_of_negative():
    """specificity == recall with pos/neg flipped (binary)."""
    import jax.numpy as jnp

    rng = np.random.default_rng(7)
    preds = rng.integers(0, 2, 100)
    target = rng.integers(0, 2, 100)
    res = specificity(jnp.asarray(preds), jnp.asarray(target), average="micro", multiclass=False)
    sk = sk_recall(1 - target, 1 - preds)
    np.testing.assert_allclose(np.asarray(res), sk, atol=1e-6)


def test_dice_micro_equals_f1_micro():
    import jax.numpy as jnp

    from metrics_tpu.ops.classification import dice

    preds, target = _input_multiclass.preds[0], _input_multiclass.target[0]
    d = dice(jnp.asarray(preds), jnp.asarray(target), average="micro")
    f = f1_score(jnp.asarray(preds), jnp.asarray(target), average="micro")
    np.testing.assert_allclose(np.asarray(d), np.asarray(f), atol=1e-6)


def test_precision_bf16_and_grad():
    t = MetricTester()
    t.run_precision_test(
        _input_multiclass_prob.preds,
        _input_multiclass_prob.target,
        metric_functional=lambda p, tt, **k: precision(p, tt, average="micro"),
    )


def test_dice_score_deprecated_alias():
    """dice_score golden from the reference docstring (functional/classification/dice.py:64-72)."""
    import warnings

    import jax.numpy as jnp

    from metrics_tpu.ops import dice_score

    pred = jnp.asarray(
        [[0.85, 0.05, 0.05, 0.05], [0.05, 0.85, 0.05, 0.05], [0.05, 0.05, 0.85, 0.05], [0.05, 0.05, 0.05, 0.85]]
    )
    target = jnp.asarray([0, 1, 3, 2])
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", DeprecationWarning)
        np.testing.assert_allclose(float(dice_score(pred, target)), 0.3333, atol=1e-4)
