"""Curve-family metrics under ``dist_sync_on_step`` / step-sync in a collective context.

Reference analog: tests/helpers/testers.py:131-171 runs every metric —
including cat-state curve metrics — with dist_sync_on_step=[False, True].
This framework splits the curve family deliberately:

- Binned* curves (sum states) are fixed-shape and run fully inside compiled
  programs — dist_sync_on_step is a psum of the TP/FP/FN grids and forward
  returns the cross-device batch value.
- Exact curves (cat states) have data-dependent output shapes, so compute —
  and therefore forward — is eager-only by design (utils/checks.py guard).
  Their step-sync story inside a compiled program is a buffered
  ``update_state`` + ``sync_states`` all_gather (parallel/sync.py:120-125),
  with compute outside the jit boundary. Both halves are tested here.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from sklearn.metrics import average_precision_score as sk_ap
from sklearn.metrics import roc_auc_score as sk_roc_auc

from metrics_tpu import AUROC, AveragePrecision, BinnedAveragePrecision, BinnedPrecisionRecallCurve
from metrics_tpu.parallel.sync import sync_axes
from metrics_tpu.utils.exceptions import MetricsUserError

pytestmark = pytest.mark.mesh8

WORLD = 8
N = 24  # samples per device


@pytest.fixture()
def mesh():
    devices = jax.devices()
    if len(devices) < WORLD:
        pytest.skip("needs 8 devices")
    return Mesh(np.asarray(devices[:WORLD]), ("data",))


def _binary_inputs(seed=11):
    rng = np.random.default_rng(seed)
    preds = rng.random((WORLD, N)).astype(np.float32)
    # force both classes on every device so per-device sklearn oracles exist
    target = rng.integers(0, 2, (WORLD, N))
    target[:, 0], target[:, 1] = 0, 1
    return jnp.asarray(preds), jnp.asarray(target)


def _run_forward(mesh, metric, preds, target):
    """One forward() per device inside shard_map; returns (WORLD,) of batch values."""

    def body(p, t):
        with sync_axes("data"):
            val = metric(p.reshape(-1, *p.shape[2:]), t.reshape(-1))
        return jnp.expand_dims(jnp.asarray(val), 0)

    return np.asarray(
        jax.jit(
            jax.shard_map(
                body, mesh=mesh, in_specs=(P("data"), P("data")), out_specs=P("data"), check_vma=False
            )
        )(preds, target)
    )


@pytest.mark.parametrize("sync_step", [False, True], ids=["local", "dist_sync_on_step"])
def test_binned_ap_forward_scope(mesh, sync_step):
    """Binned (sum-state) curve under step sync: oracle = a fresh single-device
    metric fed the global (resp. local) batch — exact, since the threshold grid
    and psum of counts commute."""
    preds, target = _binary_inputs(seed=23)
    out = _run_forward(
        mesh,
        BinnedAveragePrecision(num_classes=1, thresholds=25, dist_sync_on_step=sync_step),
        preds,
        target,
    )

    def oracle(p, t):
        m = BinnedAveragePrecision(num_classes=1, thresholds=25)
        m.update(jnp.asarray(p), jnp.asarray(t))
        return float(m.compute())

    p_np, t_np = np.asarray(preds), np.asarray(target)
    if sync_step:
        expected = np.full(WORLD, oracle(p_np.reshape(-1), t_np.reshape(-1)))
    else:
        expected = np.asarray([oracle(p_np[i], t_np[i]) for i in range(WORLD)])
    np.testing.assert_allclose(out, expected, atol=1e-6)


@pytest.mark.parametrize("sync_step", [False, True], ids=["local", "dist_sync_on_step"])
def test_binned_pr_curve_forward_scope(mesh, sync_step):
    """Full curve output (tuple state) through forward under step sync."""
    preds, target = _binary_inputs(seed=31)

    metric = BinnedPrecisionRecallCurve(num_classes=1, thresholds=11, dist_sync_on_step=sync_step)

    def body(p, t):
        with sync_axes("data"):
            prec, rec, thr = metric(p.reshape(-1), t.reshape(-1))
        return prec[None], rec[None], thr[None]

    prec, rec, _ = jax.jit(
        jax.shard_map(
            body, mesh=mesh, in_specs=(P("data"), P("data")), out_specs=P("data"), check_vma=False
        )
    )(preds, target)
    prec, rec = np.asarray(prec), np.asarray(rec)

    def oracle(p, t):
        m = BinnedPrecisionRecallCurve(num_classes=1, thresholds=11)
        m.update(jnp.asarray(p), jnp.asarray(t))
        pr, rc, _ = m.compute()
        return np.asarray(pr), np.asarray(rc)

    p_np, t_np = np.asarray(preds), np.asarray(target)
    if sync_step:
        e_prec, e_rec = oracle(p_np.reshape(-1), t_np.reshape(-1))
        for i in range(WORLD):
            np.testing.assert_allclose(prec[i], e_prec, atol=1e-6)
            np.testing.assert_allclose(rec[i], e_rec, atol=1e-6)
    else:
        for i in range(WORLD):
            e_prec, e_rec = oracle(p_np[i], t_np[i])
            np.testing.assert_allclose(prec[i], e_prec, atol=1e-6)
            np.testing.assert_allclose(rec[i], e_rec, atol=1e-6)


def test_binned_ap_epoch_state_unaffected_by_step_sync(mesh):
    """dist_sync_on_step must not change the accumulated epoch value."""
    preds, target = _binary_inputs(seed=29)
    results = {}
    for sync_step in (False, True):
        m = BinnedAveragePrecision(num_classes=1, thresholds=25, dist_sync_on_step=sync_step)

        def body(p, t):
            with sync_axes("data"):
                _ = m(p.reshape(-1), t.reshape(-1))
                state = m.sync_states(m.get_state(), "data")
                out = m.compute_state(state)
            return jnp.expand_dims(jnp.asarray(out), 0)

        out = np.asarray(
            jax.jit(
                jax.shard_map(
                    body, mesh=mesh, in_specs=(P("data"), P("data")), out_specs=P("data"), check_vma=False
                )
            )(preds, target)
        )
        results[sync_step] = out
    np.testing.assert_allclose(results[False], results[True], atol=1e-7)


@pytest.mark.parametrize(
    "metric_cls, sk_fn",
    [(AUROC, sk_roc_auc), (AveragePrecision, sk_ap)],
    ids=["auroc", "average_precision"],
)
def test_exact_curve_buffered_gather_sync(mesh, metric_cls, sk_fn):
    """Exact-curve step sync inside a compiled program: buffered update +
    all_gather of the sample buffers, compute eagerly outside. The gathered
    (global) value must match sklearn on the concatenated batch; the
    unsynced per-device values must match per-device sklearn."""
    preds, target = _binary_inputs(seed=37)
    metric = metric_cls(pos_label=1, buffer_capacity=WORLD * N)

    def body(p, t, sync):
        with sync_axes("data"):
            state = metric.update_state(metric.init_state(), p.reshape(-1), t.reshape(-1))
            if sync:
                state = metric.sync_states(state, "data")
        return state

    p_np, t_np = np.asarray(preds), np.asarray(target)

    # unsynced: per-device states out, computed eagerly per device
    states = jax.jit(
        jax.shard_map(
            lambda p, t: jax.tree.map(lambda x: x[None] if hasattr(x, "ndim") else x,
                                      body(p, t, False)),
            mesh=mesh, in_specs=(P("data"), P("data")), out_specs=P("data"), check_vma=False,
        )
    )(preds, target)
    for i in range(WORLD):
        local = jax.tree.map(lambda x: x[i] if hasattr(x, "ndim") else x, states)
        got = float(metric.compute_state(local))
        np.testing.assert_allclose(got, sk_fn(t_np[i], p_np[i]), atol=1e-6)

    # synced: gathered buffers are identical on every device; take device 0's
    synced = jax.jit(
        jax.shard_map(
            lambda p, t: body(p, t, True),
            mesh=mesh, in_specs=(P("data"), P("data")), out_specs=P(), check_vma=False,
        )
    )(preds, target)
    got = float(metric.compute_state(synced))
    np.testing.assert_allclose(got, sk_fn(t_np.reshape(-1), p_np.reshape(-1)), atol=1e-6)


def test_exact_curve_forward_in_jit_raises_actionable(mesh):
    """The design guard: exact-curve forward under jit must fail with the
    actionable message pointing at Binned* variants, not an opaque tracer error."""
    preds, target = _binary_inputs()
    with pytest.raises(MetricsUserError, match="Binned"):
        _run_forward(mesh, AUROC(pos_label=1, dist_sync_on_step=True), preds, target)
