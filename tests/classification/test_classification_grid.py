"""bf16-precision and differentiability grid over classification functionals.

Reference parity: every class metric in the reference runs fp16 + gradcheck
variants (tests/helpers/testers.py:478-570); here the same two properties —
finite results under bfloat16 inputs, finite gradients where the math is
differentiable — are asserted across the whole functional surface.
"""
import numpy as np
import pytest

from metrics_tpu import ops
from tests.classification.inputs import (
    _input_binary_prob,
    _input_multiclass_prob,
    _input_multilabel_prob,
)
from tests.helpers.testers import NUM_CLASSES, MetricTester

_t = MetricTester()
_BIN = _input_binary_prob
_MC = _input_multiclass_prob
_ML = _input_multilabel_prob


BF16_CASES = [
    ("accuracy", lambda p, t: ops.accuracy(p, t), _MC),
    ("f1", lambda p, t: ops.f1_score(p, t, num_classes=NUM_CLASSES, average="macro"), _MC),
    ("precision", lambda p, t: ops.precision(p, t, num_classes=NUM_CLASSES, average="macro"), _MC),
    ("recall", lambda p, t: ops.recall(p, t, num_classes=NUM_CLASSES, average="macro"), _MC),
    ("specificity", lambda p, t: ops.specificity(p, t, num_classes=NUM_CLASSES, average="macro"), _MC),
    ("stat_scores", lambda p, t: ops.stat_scores(p, t, num_classes=NUM_CLASSES, reduce="macro"), _MC),
    ("dice", lambda p, t: ops.dice(p, t), _MC),
    ("hamming", lambda p, t: ops.hamming_distance(p, t), _ML),
    ("confusion_matrix", lambda p, t: ops.confusion_matrix(p, t, num_classes=NUM_CLASSES), _MC),
    ("cohen_kappa", lambda p, t: ops.cohen_kappa(p, t, num_classes=NUM_CLASSES), _MC),
    ("jaccard", lambda p, t: ops.jaccard_index(p, t, num_classes=NUM_CLASSES), _MC),
    ("matthews", lambda p, t: ops.matthews_corrcoef(p, t, num_classes=NUM_CLASSES), _MC),
    ("auroc_binary", lambda p, t: ops.auroc(p, t, pos_label=1), _BIN),
    ("average_precision", lambda p, t: ops.average_precision(p, t, pos_label=1), _BIN),
    ("roc", lambda p, t: ops.roc(p, t, pos_label=1), _BIN),
    ("calibration_error", lambda p, t: ops.calibration_error(p, t), _BIN),
    ("hinge", lambda p, t: ops.hinge_loss(p, (t > 0).astype(np.int32)), _BIN),
    ("kl_divergence", None, None),  # special-cased below: needs two distributions
]


@pytest.mark.parametrize("name,fn,fixture", BF16_CASES[:-1], ids=[c[0] for c in BF16_CASES[:-1]])
def test_bf16_precision(name, fn, fixture):
    _t.run_precision_test(fixture.preds, fixture.target, fn)


def test_bf16_precision_kl_divergence():
    p = _MC.preds
    q = np.roll(_MC.preds, 1, axis=1)
    _t.run_precision_test(p, q, lambda a, b: ops.kl_divergence(a, b))


def test_differentiability_hinge():
    _t.run_differentiability_test(
        _BIN.preds, (_BIN.target > 0).astype(np.int32), lambda p, t: ops.hinge_loss(p, t)
    )


def test_differentiability_kl_divergence():
    q = np.roll(_MC.preds, 1, axis=1)
    _t.run_differentiability_test(_MC.preds, q, lambda p, t: ops.kl_divergence(p, t))
