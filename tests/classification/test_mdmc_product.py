"""Full mdmc_average × average × top_k × ignore_index product, stat-scores family.

The reference sweeps every stat-scores-derived metric over the complete
option cross-product (tests/classification/test_precision_recall.py:163-230
with the mdmc fixtures from tests/classification/inputs.py:25-80). This
module closes the same grid here against an independent numpy oracle that
re-derives the k-hot stat-scores semantics from scratch (one-hot/k-hot
matrices, column deletion for ignore_index, per-sample reduction for
``mdmc_average='samplewise'``) — no shared code with the jax implementation.
"""
import numpy as np
import pytest

from metrics_tpu.classification import F1Score, Precision, Recall, Specificity
from metrics_tpu.ops.classification import f1_score, precision, recall, specificity
from tests.classification.inputs import (
    _input_multidim_multiclass,
    _input_multidim_multiclass_prob,
)
from tests.classification.khot_oracle import khot_rows, onehot_rows
from tests.helpers.testers import NUM_CLASSES, MetricTester

_t = MetricTester()


# --------------------------------------------------------------------------- #
# independent numpy oracle
# --------------------------------------------------------------------------- #
def _counts(preds_rows, target_rows, top_k, ignore_index, micro):
    """Per-class (or micro-collapsed) tp/fp/tn/fn over a flat sample block."""
    kh = khot_rows(preds_rows, top_k, NUM_CLASSES)
    oh = onehot_rows(target_rows, NUM_CLASSES)
    if ignore_index is not None and micro:
        kh = np.delete(kh, ignore_index, axis=1)
        oh = np.delete(oh, ignore_index, axis=1)
    tp = (kh & oh).sum(0)
    fp = (kh & (1 - oh)).sum(0)
    fn = ((1 - kh) & oh).sum(0)
    tn = ((1 - kh) & (1 - oh)).sum(0)
    if micro:
        tp, fp, fn, tn = tp.sum(), fp.sum(), fn.sum(), tn.sum()
    return tp, fp, tn, fn


_NUM_DEN = {
    "precision": lambda tp, fp, tn, fn: (tp, tp + fp),
    "recall": lambda tp, fp, tn, fn: (tp, tp + fn),
    "f1": lambda tp, fp, tn, fn: (2 * tp, 2 * tp + fp + fn),
    "specificity": lambda tp, fp, tn, fn: (tn, tn + fp),
}
_WEIGHTS = {
    "precision": lambda tp, fp, tn, fn: tp + fn,
    "recall": lambda tp, fp, tn, fn: tp + fn,
    "f1": lambda tp, fp, tn, fn: tp + fn,
    "specificity": lambda tp, fp, tn, fn: tn + fp,
}


def _oracle_block(metric, preds_rows, target_rows, average, top_k, ignore_index):
    """Score one flat block of samples (post-mdmc-flattening)."""
    micro = average == "micro"
    tp, fp, tn, fn = _counts(preds_rows, target_rows, top_k, ignore_index, micro)
    num, den = _NUM_DEN[metric](tp, fp, tn, fn)
    num, den = np.asarray(num, np.float64), np.asarray(den, np.float64)
    score = np.divide(num, den, out=np.zeros_like(num), where=den != 0)
    if micro:
        return float(score)
    keep = np.ones(NUM_CLASSES, dtype=bool)
    if ignore_index is not None:
        keep[ignore_index] = False
    if average == "macro":
        return float(score[keep].mean())
    if average == "weighted":
        w = np.asarray(_WEIGHTS[metric](tp, fp, tn, fn), np.float64)[keep]
        return float(np.nan_to_num((score[keep] * w).sum() / w.sum()))
    # none: per-class vector, nan at the ignored class
    out = score.astype(np.float64)
    if ignore_index is not None:
        out[ignore_index] = np.nan
    return out


def _oracle(metric, preds, target, average, mdmc_average, top_k, ignore_index):
    """preds: (N, C, X) probs or (N, X) labels; target: (N, X)."""
    if preds.ndim == 3:  # probs: (N, C, X) -> per-sample (X, C)
        rows = lambda n: np.moveaxis(preds[n], 0, -1).reshape(-1, NUM_CLASSES)
    else:
        rows = lambda n: preds[n].reshape(-1)
    n_samples = preds.shape[0]
    if mdmc_average == "global":
        p = np.concatenate([rows(n) for n in range(n_samples)])
        t = target.reshape(-1)
        return _oracle_block(metric, p, t, average, top_k, ignore_index)
    per_sample = [
        _oracle_block(metric, rows(n), target[n].reshape(-1), average, top_k, ignore_index)
        for n in range(n_samples)
    ]
    return np.mean(np.asarray(per_sample), axis=0)


_FUNCTIONAL = {"precision": precision, "recall": recall, "f1": f1_score, "specificity": specificity}
_CLASSES = {"precision": Precision, "recall": Recall, "f1": F1Score, "specificity": Specificity}

_MDMC = _input_multidim_multiclass
_MDMC_PROB = _input_multidim_multiclass_prob

# the full grid: every (input_kind, top_k) that is type-valid
_INPUT_TOPK = [("labels", None), ("probs", None), ("probs", 2)]


def _fixture(input_kind):
    return _MDMC if input_kind == "labels" else _MDMC_PROB


@pytest.mark.parametrize("ignore_index", [None, 0])
@pytest.mark.parametrize("average", ["micro", "macro", "weighted", "none"])
@pytest.mark.parametrize("mdmc_average", ["global", "samplewise"])
@pytest.mark.parametrize("input_kind,top_k", _INPUT_TOPK)
@pytest.mark.parametrize("metric", list(_FUNCTIONAL))
def test_mdmc_product_functional(metric, input_kind, top_k, mdmc_average, average, ignore_index):
    import jax.numpy as jnp

    fix = _fixture(input_kind)
    fn = _FUNCTIONAL[metric]
    # per batch, like the reference functional tester
    for i in range(fix.preds.shape[0]):
        got = fn(
            jnp.asarray(fix.preds[i]),
            jnp.asarray(fix.target[i]),
            average=average,
            mdmc_average=mdmc_average,
            top_k=top_k,
            ignore_index=ignore_index,
            num_classes=NUM_CLASSES,
        )
        want = _oracle(
            metric, fix.preds[i], fix.target[i], average, mdmc_average, top_k, ignore_index
        )
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-5, err_msg=f"{metric} {input_kind}")


@pytest.mark.parametrize("ignore_index", [None, 0])
@pytest.mark.parametrize("average", ["micro", "macro", "weighted", "none"])
@pytest.mark.parametrize("mdmc_average", ["global", "samplewise"])
@pytest.mark.parametrize("input_kind,top_k", _INPUT_TOPK)
@pytest.mark.parametrize("ddp", [False, True])
def test_mdmc_product_f1_class(ddp, input_kind, top_k, mdmc_average, average, ignore_index):
    """F1 (the most general num/den shape) over the FULL product incl. ddp."""
    fix = _fixture(input_kind)
    _t.run_class_metric_test(
        ddp=ddp,
        preds=fix.preds,
        target=fix.target,
        metric_class=F1Score,
        sk_metric=lambda p, t: _oracle("f1", p, t, average, mdmc_average, top_k, ignore_index),
        metric_args={
            "average": average,
            "mdmc_average": mdmc_average,
            "top_k": top_k,
            "ignore_index": ignore_index,
            "num_classes": NUM_CLASSES,
        },
    )


@pytest.mark.parametrize("metric", ["precision", "recall", "specificity"])
@pytest.mark.parametrize("average", ["micro", "macro", "weighted", "none"])
@pytest.mark.parametrize("mdmc_average", ["global", "samplewise"])
def test_mdmc_product_class_ddp(metric, mdmc_average, average):
    """Remaining family members: mdmc × average cross under ddp with the
    stressing option corner (top_k=2, ignore_index=0) pinned on."""
    _t.run_class_metric_test(
        ddp=True,
        preds=_MDMC_PROB.preds,
        target=_MDMC_PROB.target,
        metric_class=_CLASSES[metric],
        sk_metric=lambda p, t: _oracle(metric, p, t, average, mdmc_average, 2, 0),
        metric_args={
            "average": average,
            "mdmc_average": mdmc_average,
            "top_k": 2,
            "ignore_index": 0,
            "num_classes": NUM_CLASSES,
        },
    )
