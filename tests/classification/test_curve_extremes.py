"""Curve metrics at class-count extremes: 1, 2, and 1000 classes.

Reference analog: the reference's curve tests sweep NUM_CLASSES=5 fixtures
(tests/classification/test_precision_recall_curve.py etc.); the extremes are
where shape handling breaks — a single class (degenerate one-hot), binary as
2-class-multiclass, and a 1000-class spread with few samples per class (most
classes unseen). Differential against sklearn throughout.
"""
import numpy as np
import pytest
from sklearn.metrics import average_precision_score, roc_curve as sk_roc

import jax.numpy as jnp

from metrics_tpu import (
    AUROC,
    AveragePrecision,
    BinnedAveragePrecision,
    BinnedPrecisionRecallCurve,
    PrecisionRecallCurve,
    ROC,
)

_rng = np.random.default_rng(8)


def _ref_pr_curve(target, probs):
    """Numpy oracle with the REFERENCE's curve semantics
    (functional/classification/precision_recall_curve.py:123-155): distinct
    descending thresholds, truncation at the FIRST index attaining full
    recall, then reversal and a final (precision=1, recall=0) point. sklearn
    >= 1.3 changed its boundary handling, so it cannot oracle the curve shape
    directly (it still oracles scalar AP/AUROC values).
    """
    order = np.argsort(-probs, kind="stable")
    probs_s, target_s = probs[order], target[order]
    distinct = np.nonzero(np.diff(probs_s))[0]
    idxs = np.r_[distinct, target_s.size - 1]
    tps = np.cumsum(target_s)[idxs].astype(np.float64)
    fps = 1 + idxs - tps
    precision = tps / (tps + fps)
    recall = tps / tps[-1]
    last = int(np.flatnonzero(tps == tps[-1])[0])
    sl = slice(0, last + 1)
    return (
        np.r_[precision[sl][::-1], 1.0],
        np.r_[recall[sl][::-1], 0.0],
        probs_s[idxs][sl][::-1],
    )


# --------------------------------------------------------------------------- #
# num_classes = 1: degenerate single-class problem
# --------------------------------------------------------------------------- #
def test_curves_single_class():
    probs = _rng.random((32, 1)).astype(np.float32)
    target = _rng.integers(0, 2, 32)  # hit/miss of THE class

    prc = PrecisionRecallCurve(num_classes=1)
    prc.update(jnp.asarray(probs), jnp.asarray(target))
    precision, recall, thresholds = prc.compute()
    p, r = np.asarray(precision, np.float64), np.asarray(recall, np.float64)
    want_p, want_r, want_th = _ref_pr_curve(target, probs[:, 0])
    np.testing.assert_allclose(p, want_p, atol=1e-6)
    np.testing.assert_allclose(r, want_r, atol=1e-6)
    np.testing.assert_allclose(np.asarray(thresholds, np.float64), want_th, atol=1e-6)

    roc = ROC(num_classes=1)
    roc.update(jnp.asarray(probs), jnp.asarray(target))
    fpr, tpr, _ = roc.compute()
    # num_classes=1 returns per-class lists of length 1. The one-vs-rest
    # loop scores class 0 as the positive class (pos_label=cls, the
    # reference's convention in _roc_compute), so sklearn's positives are
    # target==0; drop_intermediate would collapse collinear points.
    sk_fpr, sk_tpr, _ = sk_roc(target == 0, probs[:, 0], drop_intermediate=False)
    np.testing.assert_allclose(np.asarray(fpr[0], np.float64), sk_fpr, atol=1e-6)
    np.testing.assert_allclose(np.asarray(tpr[0], np.float64), sk_tpr, atol=1e-6)

    ap = AveragePrecision(num_classes=1)
    ap.update(jnp.asarray(probs), jnp.asarray(target))
    np.testing.assert_allclose(float(ap.compute()), average_precision_score(target, probs[:, 0]), atol=1e-6)


# --------------------------------------------------------------------------- #
# num_classes = 2: binary-as-multiclass consistency
# --------------------------------------------------------------------------- #
def test_curves_two_class_consistency():
    logits = _rng.normal(size=(64, 2)).astype(np.float32)
    probs = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    target = _rng.integers(0, 2, 64)

    prc = PrecisionRecallCurve(num_classes=2)
    prc.update(jnp.asarray(probs), jnp.asarray(target))
    precision, recall, _ = prc.compute()
    assert len(precision) == 2
    # class-1 curve == the reference-semantics oracle on p(class 1)
    want_p, want_r, _ = _ref_pr_curve((target == 1).astype(int), probs[:, 1])
    np.testing.assert_allclose(np.asarray(precision[1], np.float64), want_p, atol=1e-6)
    np.testing.assert_allclose(np.asarray(recall[1], np.float64), want_r, atol=1e-6)

    auroc = AUROC(num_classes=2)
    auroc.update(jnp.asarray(probs), jnp.asarray(target))
    from sklearn.metrics import roc_auc_score
    want = (roc_auc_score(target == 0, probs[:, 0]) + roc_auc_score(target == 1, probs[:, 1])) / 2
    np.testing.assert_allclose(float(auroc.compute()), want, atol=1e-6)


# --------------------------------------------------------------------------- #
# num_classes = 1000: most classes unseen
# --------------------------------------------------------------------------- #
def test_curves_thousand_classes_sparse():
    C, N = 1000, 128  # most classes have no positives
    probs = _rng.dirichlet(np.ones(C) * 0.05, size=N).astype(np.float32)
    target = _rng.integers(0, C, N)

    prc = PrecisionRecallCurve(num_classes=C)
    prc.update(jnp.asarray(probs), jnp.asarray(target))
    precision, recall, thresholds = prc.compute()
    assert len(precision) == C == len(recall) == len(thresholds)
    seen = set(np.unique(target).tolist())
    for c in list(seen)[:5]:
        p = np.asarray(precision[c], np.float64)
        r = np.asarray(recall[c], np.float64)
        want_p, want_r, _ = _ref_pr_curve((target == c).astype(int), probs[:, c])
        np.testing.assert_allclose(p, want_p, atol=1e-6, err_msg=f"class {c}")
        np.testing.assert_allclose(r, want_r, atol=1e-6, err_msg=f"class {c}")
    for c in [c for c in range(C) if c not in seen][:5]:
        # classes with no positives: curve must exist, stay in [0, 1], and
        # end at the appended (precision=1, recall=0) anchor
        p = np.asarray(precision[c], np.float64)
        assert np.isfinite(p).all() and (0 <= p).all() and (p <= 1).all()
        assert p[-1] == 1.0

    ap = AveragePrecision(num_classes=C, average="macro")
    ap.update(jnp.asarray(probs), jnp.asarray(target))
    got = float(ap.compute())
    assert 0.0 <= got <= 1.0 and np.isfinite(got)


def test_binned_curves_thousand_classes():
    C, N, TH = 1000, 128, 21
    probs = _rng.dirichlet(np.ones(C) * 0.05, size=N).astype(np.float32)
    target = _rng.integers(0, C, N)

    b = BinnedPrecisionRecallCurve(num_classes=C, thresholds=TH)
    b.update(jnp.asarray(probs), jnp.asarray(target))
    precision, recall, thresholds = b.compute()
    assert np.asarray(precision).shape == (C, TH + 1)
    assert np.asarray(recall).shape == (C, TH + 1)
    assert np.isfinite(np.asarray(precision)).all()
    # recall monotone non-increasing along thresholds for every class
    r = np.asarray(recall, np.float64)
    assert (np.diff(r[:, :-1], axis=1) <= 1e-7).all()

    bap = BinnedAveragePrecision(num_classes=C, thresholds=TH)
    bap.update(jnp.asarray(probs), jnp.asarray(target))
    vals = np.asarray(bap.compute(), np.float64)
    assert vals.shape == (C,)
    assert ((0.0 <= vals) & (vals <= 1.0)).all()


def test_binned_single_class_matches_exact_ap_ordering():
    """Binned AP at fine thresholds approaches the exact AP (1 class)."""
    # 1-d inputs: the single-class binned contract treats preds as the
    # positive-class probability ((N, 1) preds would one-hot the binary
    # target against a single class, losing the positives — same as the
    # reference's to_onehot path)
    probs = _rng.random(256).astype(np.float32)
    target = (probs + 0.3 * _rng.normal(size=256) > 0.5).astype(int)

    exact = AveragePrecision()
    exact.update(jnp.asarray(probs), jnp.asarray(target))
    want = float(exact.compute())

    binned = BinnedAveragePrecision(num_classes=1, thresholds=501)
    binned.update(jnp.asarray(probs), jnp.asarray(target))
    got = float(jnp.ravel(jnp.asarray(binned.compute()))[0])
    assert abs(got - want) < 0.02, (got, want)
