"""Specificity + HammingDistance parity over the FULL input-type zoo.

Extends the zoo treatment (tests/classification/test_input_zoo_prf.py) to the
two remaining stat-scores consumers the reference sweeps through its full
input grid: Specificity (tests/classification/test_specificity.py) and
HammingDistance (tests/classification/test_hamming_distance.py), both built
on tests/classification/inputs.py:25-80. Oracles come from the canonical
(N, C) indicator lift, same strategy as the PRF zoo.
"""
import numpy as np
import pytest

from metrics_tpu.classification import HammingDistance, Specificity
from tests.classification.inputs import _input_binary_prob, _input_multilabel_prob
from tests.classification.test_input_zoo_prf import ZOO, _canonical, _sk_stat_scores_micro
from tests.helpers.testers import THRESHOLD, MetricTester


def _sk_specificity_micro(preds, target):
    """TN / (TN + FP), derived from the PRF zoo's shared indicator counts."""
    tp, fp, tn, fn, _ = _sk_stat_scores_micro(preds, target)
    return float(tn) / max(float(tn + fp), 1.0)


def _sk_hamming(preds, target):
    """Fraction of disagreeing indicator cells (reference hamming.py:23)."""
    c_preds, c_target = _canonical(preds, target)
    return float((c_preds != c_target).mean())


@pytest.mark.parametrize("case,inputs,num_classes", ZOO, ids=[z[0] for z in ZOO])
class TestSpecificityHammingZoo(MetricTester):
    def test_specificity_micro(self, case, inputs, num_classes):
        self.run_class_metric_test(
            ddp=False,
            preds=inputs.preds,
            target=inputs.target,
            metric_class=Specificity,
            sk_metric=_sk_specificity_micro,
            metric_args={
                "average": "micro",
                "mdmc_average": "global",
                "threshold": THRESHOLD,
                "num_classes": num_classes,
            },
        )

    def test_hamming_distance(self, case, inputs, num_classes):
        self.run_class_metric_test(
            ddp=False,
            preds=inputs.preds,
            target=inputs.target,
            metric_class=HammingDistance,
            sk_metric=_sk_hamming,
            metric_args={"threshold": THRESHOLD},
        )


@pytest.mark.parametrize(
    "metric_class,sk_fn,args",
    [
        (Specificity, _sk_specificity_micro, {"average": "micro", "mdmc_average": "global", "threshold": THRESHOLD}),
        (HammingDistance, _sk_hamming, {"threshold": THRESHOLD}),
    ],
    ids=["specificity", "hamming"],
)
@pytest.mark.parametrize(
    "inputs,num_classes",
    [(_input_binary_prob, 1), (_input_multilabel_prob, 5)],
    ids=["binary_prob", "multilabel_prob"],
)
def test_zoo_ddp(metric_class, sk_fn, args, inputs, num_classes):
    """Sum-state metrics through the real collective path. Prob inputs only:
    HammingDistance has no num_classes (reference parity), so label inputs
    cannot be canonicalized under jit tracing — the class count must come
    from the trailing input dim."""
    if metric_class is Specificity:
        args = {**args, "num_classes": num_classes}
    MetricTester().run_class_metric_test(
        ddp=True,
        preds=inputs.preds,
        target=inputs.target,
        metric_class=metric_class,
        sk_metric=sk_fn,
        metric_args=args,
    )
