"""Numeric parity for the scatter-add multiclass stat-scores fast path.

``_stat_scores_update`` routes multiclass top-1 inputs through O(batch)
bincount scatters (``_stat_scores_multiclass_counts``) instead of one-hot
``(N, C)`` broadcasts. These tests pin exact count parity against the
broadcast formulation (forced by disabling the eligibility predicate) across
reduces, input kinds, masks, ignore_index, and under jit.
"""
import importlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# the `stat_scores` function re-exported by the package shadows the submodule
# attribute, so resolve the module itself for monkeypatching
ss_mod = importlib.import_module("metrics_tpu.ops.classification.stat_scores")
_multiclass_fast_path_eligible = ss_mod._multiclass_fast_path_eligible
_stat_scores_update = ss_mod._stat_scores_update


@pytest.fixture()
def force_broadcast(monkeypatch):
    """Route every call through the one-hot broadcast formulation."""
    monkeypatch.setattr(ss_mod, "_multiclass_fast_path_eligible", lambda *a, **k: False)


def _logits(n, c, seed):
    rng = np.random.default_rng(seed)
    preds = jnp.asarray(rng.standard_normal((n, c)).astype(np.float32))
    target = jnp.asarray(rng.integers(0, c, n))
    return preds, target


def _labels(n, c, seed):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, c, n)), jnp.asarray(rng.integers(0, c, n))


def _assert_counts_equal(fast, slow):
    for f, s, name in zip(fast, slow, ("tp", "fp", "tn", "fn")):
        np.testing.assert_array_equal(np.asarray(f), np.asarray(s), err_msg=name)
        assert f.shape == s.shape, name


REDUCES = ["micro", "macro", "samples"]
SHAPES = [(1, 2), (7, 3), (64, 5), (128, 100)]


@pytest.mark.parametrize("reduce", REDUCES)
@pytest.mark.parametrize("n,c", SHAPES)
def test_logit_inputs_parity(force_broadcast, reduce, n, c):
    preds, target = _logits(n, c, seed=n * 31 + c)
    assert _multiclass_fast_path_eligible(preds, target, reduce, None, None, None)
    fast = ss_mod._stat_scores_multiclass_counts(
        jnp.argmax(preds, axis=1), target, reduce, c
    )
    slow = _stat_scores_update(preds, target, reduce=reduce, num_classes=c)
    _assert_counts_equal(fast, slow)


@pytest.mark.parametrize("reduce", REDUCES)
@pytest.mark.parametrize("n,c", SHAPES)
def test_label_inputs_parity(reduce, n, c, monkeypatch):
    preds, target = _labels(n, c, seed=n * 17 + c)
    fast = _stat_scores_update(preds, target, reduce=reduce, num_classes=c)
    monkeypatch.setattr(ss_mod, "_multiclass_fast_path_eligible", lambda *a, **k: False)
    slow = _stat_scores_update(preds, target, reduce=reduce, num_classes=c)
    _assert_counts_equal(fast, slow)


@pytest.mark.parametrize("reduce", REDUCES)
def test_argmax_tie_breaking_parity(reduce, monkeypatch):
    # repeated maxima: the scatter path must pick the FIRST argmax like
    # select_topk on the broadcast path
    preds = jnp.asarray(
        [[1.0, 1.0, 0.0], [0.5, 0.7, 0.7], [2.0, 2.0, 2.0], [0.0, 1.0, 1.0]]
    )
    target = jnp.asarray([1, 2, 0, 2])
    fast = _stat_scores_update(preds, target, reduce=reduce, num_classes=3)
    monkeypatch.setattr(ss_mod, "_multiclass_fast_path_eligible", lambda *a, **k: False)
    slow = _stat_scores_update(preds, target, reduce=reduce, num_classes=3)
    _assert_counts_equal(fast, slow)


def test_macro_ignore_index_parity(monkeypatch):
    preds, target = _logits(50, 4, seed=9)
    fast = _stat_scores_update(preds, target, reduce="macro", num_classes=4, ignore_index=2)
    monkeypatch.setattr(ss_mod, "_multiclass_fast_path_eligible", lambda *a, **k: False)
    slow = _stat_scores_update(preds, target, reduce="macro", num_classes=4, ignore_index=2)
    _assert_counts_equal(fast, slow)


@pytest.mark.parametrize("reduce", REDUCES)
def test_sample_mask_matches_dropped_rows(reduce):
    # masking the tail must equal running on the unpadded prefix
    preds, target = _logits(40, 6, seed=5)
    mask = jnp.arange(40) < 29
    masked = _stat_scores_update(
        preds, target, reduce=reduce, num_classes=6, sample_mask=mask
    )
    trimmed = _stat_scores_update(preds[:29], target[:29], reduce=reduce, num_classes=6)
    if reduce == "samples":
        # masked rows report all-zero counts; compare the valid prefix
        masked = tuple(m[:29] for m in masked)
    _assert_counts_equal(trimmed, masked)


@pytest.mark.parametrize("reduce", REDUCES)
def test_jit_parity(reduce):
    preds, target = _logits(32, 5, seed=3)
    eager = _stat_scores_update(preds, target, reduce=reduce, num_classes=5)
    jitted = jax.jit(
        lambda p, t: _stat_scores_update(p, t, reduce=reduce, num_classes=5)
    )(preds, target)
    _assert_counts_equal(eager, jitted)


def test_fast_path_eligibility_boundaries():
    preds, target = _logits(8, 3, seed=0)
    assert _multiclass_fast_path_eligible(preds, target, "macro", None, None, None)
    assert _multiclass_fast_path_eligible(preds, target, "macro", 1, None, None)
    # broadcast-only configurations must be rejected
    assert not _multiclass_fast_path_eligible(preds, target, "macro", 2, None, None)
    assert not _multiclass_fast_path_eligible(preds, target, "macro", None, False, None)
    assert not _multiclass_fast_path_eligible(preds, target, "micro", None, None, 0)
    probs = jnp.asarray(np.random.default_rng(0).random(8).astype(np.float32))
    binary = jnp.asarray(np.random.default_rng(1).integers(0, 2, 8))
    assert not _multiclass_fast_path_eligible(probs, binary, "micro", None, None, None)
    ml_target = jnp.asarray(np.random.default_rng(2).integers(0, 2, (8, 3)))
    assert not _multiclass_fast_path_eligible(preds, ml_target, "micro", None, None, None)
