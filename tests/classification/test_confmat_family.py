"""ConfusionMatrix / Jaccard / CohenKappa / Matthews / Hamming / StatScores parity.

Reference parity: tests/classification/test_confusion_matrix.py, test_jaccard.py,
test_cohen_kappa.py, test_matthews_corrcoef.py, test_hamming_distance.py,
test_stat_scores.py (compacted).
"""
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import cohen_kappa_score as sk_kappa
from sklearn.metrics import confusion_matrix as sk_confmat
from sklearn.metrics import jaccard_score as sk_jaccard
from sklearn.metrics import matthews_corrcoef as sk_mcc
from sklearn.metrics import multilabel_confusion_matrix as sk_ml_confmat

from metrics_tpu.classification import (
    CohenKappa,
    ConfusionMatrix,
    HammingDistance,
    JaccardIndex,
    MatthewsCorrCoef,
    StatScores,
)
from metrics_tpu.ops.classification import (
    cohen_kappa,
    confusion_matrix,
    hamming_distance,
    jaccard_index,
    matthews_corrcoef,
    stat_scores,
)
from tests.classification.inputs import _input_multiclass, _input_multiclass_prob, _input_multilabel_prob
from tests.helpers.testers import NUM_CLASSES, THRESHOLD, MetricTester


def _sk_cm(preds, target, normalize=None):
    if preds.ndim == target.ndim + 1:
        preds = np.argmax(preds, axis=-1)
    return sk_confmat(target.reshape(-1), preds.reshape(-1), labels=range(NUM_CLASSES), normalize=normalize)


@pytest.mark.parametrize("normalize", [None, "true", "pred", "all"])
@pytest.mark.parametrize("ddp", [False, True])
def test_confusion_matrix(ddp, normalize):
    MetricTester().run_class_metric_test(
        ddp=ddp,
        preds=_input_multiclass.preds,
        target=_input_multiclass.target,
        metric_class=ConfusionMatrix,
        sk_metric=lambda p, t: _sk_cm(p, t, normalize),
        metric_args={"num_classes": NUM_CLASSES, "normalize": normalize},
    )


def test_confusion_matrix_multilabel():
    preds = _input_multilabel_prob.preds[0]
    target = _input_multilabel_prob.target[0]
    res = confusion_matrix(jnp.asarray(preds), jnp.asarray(target), num_classes=NUM_CLASSES, threshold=THRESHOLD, multilabel=True)
    sk = sk_ml_confmat(target, (preds >= THRESHOLD).astype(int))
    np.testing.assert_allclose(np.asarray(res), sk)


@pytest.mark.parametrize("average", ["macro", "micro", "weighted", None])
def test_jaccard(average):
    preds, target = _input_multiclass.preds[0], _input_multiclass.target[0]
    res = jaccard_index(jnp.asarray(preds), jnp.asarray(target), num_classes=NUM_CLASSES, average=average)
    sk = sk_jaccard(target, preds, average=average if average else None, labels=range(NUM_CLASSES))
    np.testing.assert_allclose(np.asarray(res), sk, atol=1e-6)


@pytest.mark.parametrize("weights", [None, "linear", "quadratic"])
def test_cohen_kappa(weights):
    preds, target = _input_multiclass.preds[0], _input_multiclass.target[0]
    res = cohen_kappa(jnp.asarray(preds), jnp.asarray(target), num_classes=NUM_CLASSES, weights=weights)
    sk = sk_kappa(target, preds, weights=weights)
    np.testing.assert_allclose(np.asarray(res), sk, atol=1e-6)


@pytest.mark.parametrize("ddp", [False, True])
def test_matthews(ddp):
    MetricTester().run_class_metric_test(
        ddp=ddp,
        preds=_input_multiclass.preds,
        target=_input_multiclass.target,
        metric_class=MatthewsCorrCoef,
        sk_metric=lambda p, t: sk_mcc(t.reshape(-1), p.reshape(-1)),
        metric_args={"num_classes": NUM_CLASSES},
    )


def test_hamming():
    preds, target = _input_multilabel_prob.preds[0], _input_multilabel_prob.target[0]
    res = hamming_distance(jnp.asarray(preds), jnp.asarray(target), threshold=THRESHOLD)
    expected = 1 - np.mean((preds >= THRESHOLD).astype(int) == target)
    np.testing.assert_allclose(np.asarray(res), expected, atol=1e-6)


def test_stat_scores_macro_vs_sklearn():
    preds, target = _input_multiclass.preds[0], _input_multiclass.target[0]
    res = np.asarray(stat_scores(jnp.asarray(preds), jnp.asarray(target), reduce="macro", num_classes=NUM_CLASSES))
    mlc = sk_ml_confmat(target, preds, labels=range(NUM_CLASSES))  # (C, 2, 2): [[tn, fp], [fn, tp]]
    expected = np.stack([mlc[:, 1, 1], mlc[:, 0, 1], mlc[:, 0, 0], mlc[:, 1, 0], mlc[:, 1, 1] + mlc[:, 1, 0]], axis=1)
    np.testing.assert_array_equal(res, expected)


@pytest.mark.parametrize("ddp", [False, True])
def test_stat_scores_class(ddp):
    def _sk(p, t):
        mlc = sk_ml_confmat(t, p, labels=range(NUM_CLASSES))
        return np.stack([mlc[:, 1, 1], mlc[:, 0, 1], mlc[:, 0, 0], mlc[:, 1, 0], mlc[:, 1, 1] + mlc[:, 1, 0]], axis=1)

    MetricTester().run_class_metric_test(
        ddp=ddp,
        preds=_input_multiclass.preds,
        target=_input_multiclass.target,
        metric_class=StatScores,
        sk_metric=_sk,
        metric_args={"reduce": "macro", "num_classes": NUM_CLASSES},
    )


def test_stat_scores_ignore_index():
    preds = jnp.asarray([1, 0, 2, 1])
    target = jnp.asarray([1, 1, 2, 0])
    res = np.asarray(stat_scores(preds, target, reduce="macro", num_classes=3, ignore_index=0))
    assert (res[0] == -1).all()  # ignored class marked
    # micro drops the ignored column
    res_micro = np.asarray(stat_scores(preds, target, reduce="micro", num_classes=3, ignore_index=0))
    expected = stat_scores(preds, target, reduce="micro", num_classes=3)
    assert res_micro.shape == (5,)


def test_negative_ignore_index_mdmc_labels():
    """Regression: negative ignore_index with integer multidim-multiclass inputs."""
    from metrics_tpu.ops.classification import accuracy

    preds = jnp.asarray([[0, 1, 2, 1], [2, 0, 1, 0]])
    target = jnp.asarray([[0, 1, -1, 1], [2, -1, 1, 0]])
    res = accuracy(preds, target, num_classes=3, mdmc_average="global", ignore_index=-1)
    valid = np.asarray(target) != -1
    expected = (np.asarray(preds)[valid] == np.asarray(target)[valid]).mean()
    np.testing.assert_allclose(np.asarray(res), expected, atol=1e-6)


# ---- input-zoo extensions (binary + logits + multilabel variants) ---------- #
def test_confusion_matrix_binary_prob():
    from tests.classification.inputs import _input_binary_prob

    preds, target = _input_binary_prob.preds[0], _input_binary_prob.target[0]
    res = confusion_matrix(jnp.asarray(preds), jnp.asarray(target), num_classes=2, threshold=THRESHOLD)
    sk = sk_confmat(target, (preds >= THRESHOLD).astype(int), labels=[0, 1])
    np.testing.assert_array_equal(np.asarray(res), sk)


def test_confusion_matrix_multiclass_logits():
    from tests.classification.inputs import _input_multiclass_logits

    preds, target = _input_multiclass_logits.preds[0], _input_multiclass_logits.target[0]
    res = confusion_matrix(jnp.asarray(preds), jnp.asarray(target), num_classes=NUM_CLASSES)
    sk = sk_confmat(target, np.argmax(preds, -1), labels=range(NUM_CLASSES))
    np.testing.assert_array_equal(np.asarray(res), sk)


def test_cohen_kappa_binary():
    from tests.classification.inputs import _input_binary

    preds, target = _input_binary.preds[0], _input_binary.target[0]
    res = cohen_kappa(jnp.asarray(preds), jnp.asarray(target), num_classes=2)
    np.testing.assert_allclose(np.asarray(res), sk_kappa(target, preds), atol=1e-6)


def test_jaccard_multilabel():
    from tests.classification.inputs import _input_multilabel_prob

    preds, target = _input_multilabel_prob.preds[0], _input_multilabel_prob.target[0]
    res = jaccard_index(jnp.asarray(preds), jnp.asarray(target), num_classes=2, threshold=THRESHOLD)
    hard = (preds >= THRESHOLD).astype(int).reshape(-1)
    sk = sk_jaccard(target.reshape(-1), hard, average="macro")  # macro over {neg, pos} of the flattened lift
    np.testing.assert_allclose(np.asarray(res), sk, atol=1e-6)


def test_matthews_binary_logits():
    from tests.classification.inputs import _input_binary_logits

    preds, target = _input_binary_logits.preds[0], _input_binary_logits.target[0]
    res = matthews_corrcoef(jnp.asarray(preds), jnp.asarray(target), num_classes=2, threshold=THRESHOLD)
    # the reference thresholds binary decision values at the RAW threshold in
    # this path (no sigmoid) — verified against the reference implementation
    hard = (preds >= THRESHOLD).astype(int)
    np.testing.assert_allclose(np.asarray(res), sk_mcc(target, hard), atol=1e-6)


def test_hamming_multidim():
    from tests.classification.inputs import _input_multilabel_multidim

    preds, target = _input_multilabel_multidim.preds[0], _input_multilabel_multidim.target[0]
    res = hamming_distance(jnp.asarray(preds), jnp.asarray(target))
    np.testing.assert_allclose(np.asarray(res), (preds != target).mean(), atol=1e-6)
