"""Seeded input fixtures (reference parity: tests/classification/inputs.py)."""
from collections import namedtuple

import numpy as np

from tests.helpers.testers import BATCH_SIZE, EXTRA_DIM, NUM_BATCHES, NUM_CLASSES

Input = namedtuple("Input", ["preds", "target"])

_rng = np.random.default_rng(42)

_input_binary_prob = Input(
    preds=_rng.random((NUM_BATCHES, BATCH_SIZE)).astype(np.float32),
    target=_rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE)),
)
_input_binary = Input(
    preds=_rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE)),
    target=_rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE)),
)
_input_multilabel_prob = Input(
    preds=_rng.random((NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)).astype(np.float32),
    target=_rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)),
)
_input_multilabel = Input(
    preds=_rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)),
    target=_rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)),
)


def _softmax(x, axis):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


_input_multiclass_prob = Input(
    preds=_softmax(_rng.random((NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)).astype(np.float32), axis=-1),
    target=_rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE)),
)
_input_multiclass = Input(
    preds=_rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE)),
    target=_rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE)),
)
_input_multidim_multiclass_prob = Input(
    preds=_softmax(_rng.random((NUM_BATCHES, BATCH_SIZE, NUM_CLASSES, EXTRA_DIM)).astype(np.float32), axis=2),
    target=_rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE, EXTRA_DIM)),
)
_input_multidim_multiclass = Input(
    preds=_rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE, EXTRA_DIM)),
    target=_rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE, EXTRA_DIM)),
)

# logit-valued and multi-dim multilabel variants + the no-match edge case
# (reference inputs.py:33-35,43-67)
_input_binary_logits = Input(
    preds=_rng.normal(size=(NUM_BATCHES, BATCH_SIZE)).astype(np.float32),
    target=_rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE)),
)
_input_multilabel_logits = Input(
    preds=_rng.normal(size=(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)).astype(np.float32),
    target=_rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)),
)
_input_multiclass_logits = Input(
    preds=(10 * _rng.normal(size=(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES))).astype(np.float32),
    target=_rng.integers(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE)),
)
_input_multilabel_multidim_prob = Input(
    preds=_rng.random((NUM_BATCHES, BATCH_SIZE, NUM_CLASSES, EXTRA_DIM)).astype(np.float32),
    target=_rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES, EXTRA_DIM)),
)
_input_multilabel_multidim = Input(
    preds=_rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES, EXTRA_DIM)),
    target=_rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES, EXTRA_DIM)),
)
__no_match_preds = _rng.integers(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES))
_input_multilabel_no_match = Input(preds=__no_match_preds, target=1 - __no_match_preds)
