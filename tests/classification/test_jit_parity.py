"""jit-vs-eager parity sweep over the classification functional surface.

The canonicalization machine splits static shape dispatch (always traceable)
from value checks (eager-only) — utils/checks.py. This sweep asserts that,
with ``num_classes`` given, jitting each functional neither raises nor
changes the result on any input type. Tracer leaks (python branches on
concrete values, host round-trips) fail loudly here.
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import ops
from tests.classification.inputs import (
    _input_binary_prob,
    _input_multiclass,
    _input_multiclass_prob,
    _input_multidim_multiclass,
    _input_multilabel_prob,
)
from tests.helpers.testers import NUM_CLASSES

_MC = dict(num_classes=NUM_CLASSES)

CASES = [
    ("accuracy_mc_prob", lambda p, t: ops.accuracy(p, t, **_MC), _input_multiclass_prob),
    ("accuracy_mc_labels", lambda p, t: ops.accuracy(p, t, **_MC), _input_multiclass),
    ("accuracy_mdmc", lambda p, t: ops.accuracy(p, t, mdmc_average="global", **_MC), _input_multidim_multiclass),
    ("accuracy_binary", lambda p, t: ops.accuracy(p, t, num_classes=1), _input_binary_prob),
    ("accuracy_multilabel", lambda p, t: ops.accuracy(p, t), _input_multilabel_prob),
    ("accuracy_top2", lambda p, t: ops.accuracy(p, t, top_k=2, **_MC), _input_multiclass_prob),
    ("f1_macro", lambda p, t: ops.f1_score(p, t, average="macro", **_MC), _input_multiclass_prob),
    ("fbeta_weighted", lambda p, t: ops.fbeta_score(p, t, beta=0.5, average="weighted", **_MC), _input_multiclass_prob),
    ("precision_none", lambda p, t: ops.precision(p, t, average="none", **_MC), _input_multiclass_prob),
    ("recall_samples", lambda p, t: ops.recall(p, t, average="samples", **_MC), _input_multilabel_prob),
    ("specificity", lambda p, t: ops.specificity(p, t, average="macro", **_MC), _input_multiclass_prob),
    ("stat_scores", lambda p, t: ops.stat_scores(p, t, reduce="macro", **_MC), _input_multiclass_prob),
    ("stat_scores_ignore", lambda p, t: ops.stat_scores(p, t, reduce="macro", ignore_index=0, **_MC), _input_multiclass),
    ("dice", lambda p, t: ops.dice(p, t, **_MC), _input_multiclass),
    ("hamming", lambda p, t: ops.hamming_distance(p, t), _input_multilabel_prob),
    ("confusion_matrix", lambda p, t: ops.confusion_matrix(p, t, **_MC), _input_multiclass),
    ("confmat_normalized", lambda p, t: ops.confusion_matrix(p, t, normalize="true", **_MC), _input_multiclass),
    ("cohen_kappa", lambda p, t: ops.cohen_kappa(p, t, **_MC), _input_multiclass),
    ("jaccard", lambda p, t: ops.jaccard_index(p, t, **_MC), _input_multiclass),
    ("matthews", lambda p, t: ops.matthews_corrcoef(p, t, **_MC), _input_multiclass),
    ("hinge", lambda p, t: ops.hinge_loss(p, (t > 0).astype(np.int32)), _input_binary_prob),
    ("kl_div", lambda p, t: ops.kl_divergence(p, jnp.roll(p, 1, axis=0)), _input_multiclass_prob),
    ("calibration", lambda p, t: ops.calibration_error(p, t), _input_binary_prob),
]


@pytest.mark.parametrize("name,fn,fixture", CASES, ids=[c[0] for c in CASES])
def test_jit_matches_eager(name, fn, fixture):
    preds = jnp.asarray(fixture.preds[0])
    target = jnp.asarray(fixture.target[0])
    eager = fn(preds, target)
    jitted = jax.jit(fn)(preds, target)
    np.testing.assert_allclose(np.asarray(jitted), np.asarray(eager), rtol=1e-6, atol=1e-6)


def test_curve_functionals_raise_actionably_under_jit():
    """Exact curves are eager-only by design (data-dependent shapes); under
    jit they must raise the actionable pointer to the Binned* variants, not
    an opaque tracer error."""
    from metrics_tpu.utils.exceptions import MetricsUserError

    preds = jnp.asarray(_input_binary_prob.preds[0])
    target = jnp.asarray(_input_binary_prob.target[0])
    for fn in (
        lambda p, t: ops.auroc(p, t, pos_label=1),
        lambda p, t: ops.average_precision(p, t, pos_label=1),
        lambda p, t: ops.roc(p, t, pos_label=1),
    ):
        fn(preds, target)  # eager path stays fine
        with pytest.raises(MetricsUserError, match="Binned"):
            jax.jit(fn)(preds, target)


def test_weighted_multiclass_auroc_raises_actionably_under_jit():
    from metrics_tpu.utils.exceptions import MetricsUserError

    preds = jnp.asarray(_input_multiclass_prob.preds[0])
    target = jnp.asarray(_input_multiclass_prob.target[0])
    fn = lambda p, t: ops.auroc(p, t, num_classes=NUM_CLASSES, average="weighted")
    fn(preds, target)  # eager fine
    with pytest.raises(MetricsUserError, match="Binned"):
        jax.jit(fn)(preds, target)
