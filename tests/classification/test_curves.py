"""Curve-metric parity: ROC / PR-curve / AUC / AUROC / AveragePrecision / binned.

Reference parity: tests/classification/test_roc.py, test_precision_recall_curve.py,
test_auc.py, test_auroc.py, test_average_precision.py, test_binned_precision_recall.py.
"""
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import auc as sk_auc
from sklearn.metrics import average_precision_score as sk_ap
from sklearn.metrics import precision_recall_curve as sk_prc
from sklearn.metrics import roc_auc_score as sk_roc_auc
from sklearn.metrics import roc_curve as sk_roc

from metrics_tpu.classification import AUC, AUROC, AveragePrecision, BinnedAveragePrecision, BinnedPrecisionRecallCurve, PrecisionRecallCurve, ROC
from metrics_tpu.ops.classification import auc, auroc, average_precision, precision_recall_curve, roc
from tests.classification.inputs import _input_binary_prob, _input_multiclass_prob
from tests.helpers.testers import NUM_CLASSES, MetricTester


def test_roc_binary():
    preds, target = _input_binary_prob.preds[0], _input_binary_prob.target[0]
    fpr, tpr, thr = roc(jnp.asarray(preds), jnp.asarray(target))
    sk_fpr, sk_tpr, sk_thr = sk_roc(target, preds, drop_intermediate=False)
    np.testing.assert_allclose(np.asarray(fpr), sk_fpr, atol=1e-6)
    np.testing.assert_allclose(np.asarray(tpr), sk_tpr, atol=1e-6)


def _sk_prc_tm_convention(target, preds):
    """sklearn>=1.1 keeps all full-recall points; the reference convention
    (torchmetrics 0.9 == sklearn<1.1) keeps only the highest-threshold one."""
    sk_p, sk_r, sk_t = sk_prc(target, preds)
    k = int(np.where(sk_r == 1.0)[0][-1]) if (sk_r == 1.0).any() else 0
    return sk_p[k:], sk_r[k:], sk_t[k:]


def test_prc_binary():
    preds, target = _input_binary_prob.preds[0], _input_binary_prob.target[0]
    p, r, t = precision_recall_curve(jnp.asarray(preds), jnp.asarray(target))
    sk_p, sk_r, sk_t = _sk_prc_tm_convention(target, preds)
    np.testing.assert_allclose(np.asarray(p), sk_p, atol=1e-6)
    np.testing.assert_allclose(np.asarray(r), sk_r, atol=1e-6)
    np.testing.assert_allclose(np.asarray(t), sk_t, atol=1e-6)


def test_auc_vs_sklearn():
    x = np.sort(np.random.default_rng(3).random(20))
    y = np.random.default_rng(4).random(20)
    np.testing.assert_allclose(np.asarray(auc(jnp.asarray(x), jnp.asarray(y))), sk_auc(x, y), atol=1e-6)


@pytest.mark.parametrize("ddp", [False, True])
def test_auroc_binary(ddp):
    MetricTester().run_class_metric_test(
        ddp=ddp,
        preds=_input_binary_prob.preds,
        target=_input_binary_prob.target,
        metric_class=AUROC,
        sk_metric=lambda p, t: sk_roc_auc(t, p),
        metric_args={},
        check_batch=False,
    )


@pytest.mark.parametrize("average", ["macro", "weighted"])
def test_auroc_multiclass(average):
    preds = _input_multiclass_prob.preds.reshape(-1, NUM_CLASSES)
    target = _input_multiclass_prob.target.reshape(-1)
    res = auroc(jnp.asarray(preds), jnp.asarray(target), num_classes=NUM_CLASSES, average=average)
    sk = sk_roc_auc(target, preds, multi_class="ovr", average="macro" if average == "macro" else "weighted", labels=range(NUM_CLASSES))
    np.testing.assert_allclose(np.asarray(res), sk, atol=1e-5)


def test_auroc_max_fpr():
    preds, target = _input_binary_prob.preds[0], _input_binary_prob.target[0]
    res = auroc(jnp.asarray(preds), jnp.asarray(target), max_fpr=0.5)
    sk = sk_roc_auc(target, preds, max_fpr=0.5)
    np.testing.assert_allclose(np.asarray(res), sk, atol=1e-5)


@pytest.mark.parametrize("ddp", [False, True])
def test_average_precision_binary(ddp):
    MetricTester().run_class_metric_test(
        ddp=ddp,
        preds=_input_binary_prob.preds,
        target=_input_binary_prob.target,
        metric_class=AveragePrecision,
        sk_metric=lambda p, t: sk_ap(t, p),
        metric_args={},
        check_batch=False,
    )


def test_average_precision_multiclass_macro():
    preds = _input_multiclass_prob.preds.reshape(-1, NUM_CLASSES)
    target = _input_multiclass_prob.target.reshape(-1)
    res = average_precision(jnp.asarray(preds), jnp.asarray(target), num_classes=NUM_CLASSES, average="macro")
    per_class = [sk_ap((target == c).astype(int), preds[:, c]) for c in range(NUM_CLASSES)]
    np.testing.assert_allclose(np.asarray(res), np.nanmean(per_class), atol=1e-5)


def test_roc_class_accumulates():
    m = ROC()
    for i in range(4):
        m.update(jnp.asarray(_input_binary_prob.preds[i]), jnp.asarray(_input_binary_prob.target[i]))
    fpr, tpr, thr = m.compute()
    all_p = _input_binary_prob.preds[:4].reshape(-1)
    all_t = _input_binary_prob.target[:4].reshape(-1)
    sk_fpr, sk_tpr, _ = sk_roc(all_t, all_p, drop_intermediate=False)
    np.testing.assert_allclose(np.asarray(fpr), sk_fpr, atol=1e-6)
    np.testing.assert_allclose(np.asarray(tpr), sk_tpr, atol=1e-6)


# --------------------------------------------------------------------------- #
# binned variants (reference docstring values, binned_precision_recall.py:71-110)
# --------------------------------------------------------------------------- #
def test_binned_pr_curve_binary_docstring():
    pred = jnp.asarray([0, 0.1, 0.8, 0.4])
    target = jnp.asarray([0, 1, 1, 0])
    pr_curve = BinnedPrecisionRecallCurve(num_classes=1, thresholds=5)
    precision, recall, thresholds = pr_curve(pred, target)
    np.testing.assert_allclose(np.asarray(precision), [0.5, 0.5, 1.0, 1.0, 1.0, 1.0], atol=1e-5)
    np.testing.assert_allclose(np.asarray(recall), [1.0, 0.5, 0.5, 0.5, 0.0, 0.0], atol=1e-5)
    np.testing.assert_allclose(np.asarray(thresholds), [0.0, 0.25, 0.5, 0.75, 1.0], atol=1e-6)


def test_binned_ap_binary_docstring():
    pred = jnp.asarray([0.0, 1.0, 2.0, 3.0])
    target = jnp.asarray([0, 1, 1, 1])
    ap = BinnedAveragePrecision(num_classes=1, thresholds=10)
    res = ap(pred, target)
    np.testing.assert_allclose(np.asarray(res), 1.0, atol=1e-4)


def test_binned_pr_is_jittable():
    import jax

    m = BinnedPrecisionRecallCurve(num_classes=NUM_CLASSES, thresholds=20)
    f = jax.jit(lambda s, p, t: m.update_state(s, p, t))
    state = m.init_state()
    preds = jnp.asarray(_input_multiclass_prob.preds[0])
    target = jnp.asarray(_input_multiclass_prob.target[0])
    state = f(state, preds, target)
    state = f(state, preds, target)
    p, r, t = m.compute_state(state)
    assert len(p) == NUM_CLASSES


def test_auroc_multilabel_macro_vs_sklearn():
    import numpy as np
    from sklearn.metrics import roc_auc_score

    rng = np.random.default_rng(5)
    preds = rng.uniform(size=(64, 4)).astype(np.float32)
    target = rng.integers(0, 2, (64, 4))
    res = auroc(jnp.asarray(preds), jnp.asarray(target), num_classes=4, average="macro")
    np.testing.assert_allclose(np.asarray(res), roc_auc_score(target, preds, average="macro"), atol=1e-6)


@pytest.mark.parametrize("average", [None, "none"])
def test_auroc_multiclass_per_class_vs_sklearn(average):
    """average=None is the reference's per-class alias (reference auroc.py:161)."""
    import numpy as np
    from sklearn.metrics import roc_auc_score

    rng = np.random.default_rng(5)
    preds = rng.uniform(size=(64, 4))
    preds = (preds / preds.sum(1, keepdims=True)).astype(np.float32)
    target = rng.integers(0, 4, 64)
    res = auroc(jnp.asarray(preds), jnp.asarray(target), num_classes=4, average=average)
    sk = roc_auc_score(target, preds, average=None, multi_class="ovr", labels=range(4))
    np.testing.assert_allclose(np.asarray(res), sk, atol=1e-6)


def test_average_precision_multiclass_per_class_vs_sklearn():
    import numpy as np
    from sklearn.metrics import average_precision_score

    rng = np.random.default_rng(5)
    preds = rng.uniform(size=(64, 4))
    preds = (preds / preds.sum(1, keepdims=True)).astype(np.float32)
    target = rng.integers(0, 4, 64)
    res = average_precision(jnp.asarray(preds), jnp.asarray(target), num_classes=4, average=None)
    onehot = np.eye(4)[target]
    sk = [average_precision_score(onehot[:, c], preds[:, c]) for c in range(4)]
    np.testing.assert_allclose([float(x) for x in res], sk, atol=1e-6)


def test_roc_multiclass_per_class_vs_sklearn():
    """Per-class curves keep every threshold (the reference does not drop
    collinear points, unlike sklearn's default drop_intermediate=True)."""
    import numpy as np

    from metrics_tpu.ops.classification import roc as roc_fn

    rng = np.random.default_rng(6)
    preds = rng.uniform(size=(64, 3))
    preds = (preds / preds.sum(1, keepdims=True)).astype(np.float32)
    target = rng.integers(0, 3, 64)
    fprs, tprs, _ = roc_fn(jnp.asarray(preds), jnp.asarray(target), num_classes=3)
    for c in range(3):
        sk_fpr, sk_tpr, _ = sk_roc((target == c).astype(int), preds[:, c], drop_intermediate=False)
        np.testing.assert_allclose(np.asarray(fprs[c]), sk_fpr, atol=1e-6)
        np.testing.assert_allclose(np.asarray(tprs[c]), sk_tpr, atol=1e-6)
