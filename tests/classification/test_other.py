"""Calibration / Hinge / KL / ranking parity.

Reference parity: tests/classification/test_calibration_error.py, test_hinge.py,
test_kl_divergence.py, test_ranking.py.
"""
import jax.numpy as jnp
import numpy as np
import pytest
from scipy.special import rel_entr
from scipy.stats import entropy as scipy_entropy
from sklearn.metrics import coverage_error as sk_coverage
from sklearn.metrics import hinge_loss as sk_hinge
from sklearn.metrics import label_ranking_average_precision_score as sk_lrap
from sklearn.metrics import label_ranking_loss as sk_lrl

from metrics_tpu.classification import CalibrationError, CoverageError, HingeLoss, KLDivergence, LabelRankingAveragePrecision, LabelRankingLoss
from metrics_tpu.ops.classification import calibration_error, coverage_error, hinge_loss, kl_divergence, label_ranking_average_precision, label_ranking_loss
from tests.classification.inputs import _input_binary_prob, _input_multiclass_prob, _input_multilabel_prob
from tests.helpers.testers import NUM_CLASSES, MetricTester

_rng = np.random.default_rng(11)


def _np_ece(confidences, accuracies, n_bins=15, norm="l1"):
    bins = np.linspace(0, 1, n_bins + 1)
    idx = np.clip(np.searchsorted(bins, confidences, side="left") - 1, 0, n_bins - 1)
    ce = 0.0
    maxe = 0.0
    for b in range(n_bins):
        mask = idx == b
        if mask.sum() == 0:
            continue
        acc, conf, prop = accuracies[mask].mean(), confidences[mask].mean(), mask.mean()
        if norm == "l1":
            ce += abs(acc - conf) * prop
        elif norm == "l2":
            ce += (acc - conf) ** 2 * prop
        maxe = max(maxe, abs(acc - conf))
    if norm == "max":
        return maxe
    if norm == "l2":
        return np.sqrt(ce) if ce > 0 else 0.0
    return ce


@pytest.mark.parametrize("norm", ["l1", "l2", "max"])
def test_calibration_binary(norm):
    preds, target = _input_binary_prob.preds[0], _input_binary_prob.target[0]
    res = calibration_error(jnp.asarray(preds), jnp.asarray(target), norm=norm)
    expected = _np_ece(preds, target.astype(float), norm=norm)
    np.testing.assert_allclose(np.asarray(res), expected, atol=1e-6)


@pytest.mark.parametrize("norm", ["l1", "max"])
def test_calibration_multiclass(norm):
    preds, target = _input_multiclass_prob.preds[0], _input_multiclass_prob.target[0]
    res = calibration_error(jnp.asarray(preds), jnp.asarray(target), norm=norm)
    conf = preds.max(-1)
    acc = (preds.argmax(-1) == target).astype(float)
    expected = _np_ece(conf, acc, norm=norm)
    np.testing.assert_allclose(np.asarray(res), expected, atol=1e-6)


def test_calibration_class_ddp():
    MetricTester().run_class_metric_test(
        ddp=True,
        preds=_input_binary_prob.preds,
        target=_input_binary_prob.target,
        metric_class=CalibrationError,
        sk_metric=lambda p, t: _np_ece(p, t.astype(float)),
        metric_args={},
        check_batch=False,
    )


def test_hinge_binary():
    preds = _rng.standard_normal(100).astype(np.float32)
    target = _rng.integers(0, 2, 100)
    res = hinge_loss(jnp.asarray(preds), jnp.asarray(target))
    sk = sk_hinge(np.where(target == 0, -1, 1), preds)
    np.testing.assert_allclose(np.asarray(res), sk, atol=1e-6)


def test_hinge_multiclass_crammer_singer():
    preds = _rng.standard_normal((60, NUM_CLASSES)).astype(np.float32)
    target = _rng.integers(0, NUM_CLASSES, 60)
    res = hinge_loss(jnp.asarray(preds), jnp.asarray(target))
    sk = sk_hinge(target, preds, labels=range(NUM_CLASSES))
    np.testing.assert_allclose(np.asarray(res), sk, atol=1e-5)


def test_kl_divergence():
    p = _rng.random((32, 8)).astype(np.float32)
    q = _rng.random((32, 8)).astype(np.float32)
    res = kl_divergence(jnp.asarray(p), jnp.asarray(q))
    pn = p / p.sum(-1, keepdims=True)
    qn = q / q.sum(-1, keepdims=True)
    expected = np.mean([scipy_entropy(pn[i], qn[i]) for i in range(len(p))])
    np.testing.assert_allclose(np.asarray(res), expected, atol=1e-5)


def test_kl_module_accumulates():
    m = KLDivergence()
    p = _rng.random((16, 4)).astype(np.float32)
    q = _rng.random((16, 4)).astype(np.float32)
    m.update(jnp.asarray(p[:8]), jnp.asarray(q[:8]))
    m.update(jnp.asarray(p[8:]), jnp.asarray(q[8:]))
    pn = p / p.sum(-1, keepdims=True)
    qn = q / q.sum(-1, keepdims=True)
    expected = np.mean([scipy_entropy(pn[i], qn[i]) for i in range(len(p))])
    np.testing.assert_allclose(np.asarray(m.compute()), expected, atol=1e-5)


@pytest.mark.parametrize(
    "tm_fn,sk_fn",
    [
        (coverage_error, sk_coverage),
        (label_ranking_average_precision, sk_lrap),
        (label_ranking_loss, sk_lrl),
    ],
)
def test_ranking_functional(tm_fn, sk_fn):
    preds = _rng.random((40, 6)).astype(np.float32)
    target = _rng.integers(0, 2, (40, 6))
    res = tm_fn(jnp.asarray(preds), jnp.asarray(target))
    sk = sk_fn(target, preds)
    np.testing.assert_allclose(np.asarray(res), sk, atol=1e-5)


@pytest.mark.parametrize("cls,sk_fn", [(CoverageError, sk_coverage), (LabelRankingAveragePrecision, sk_lrap), (LabelRankingLoss, sk_lrl)])
@pytest.mark.parametrize("ddp", [False, True])
def test_ranking_class(cls, sk_fn, ddp):
    preds = _rng.random((8, 16, 6)).astype(np.float32)
    target = _rng.integers(0, 2, (8, 16, 6))
    # guard against degenerate rows (all 0 / all 1) for sklearn parity
    target[:, :, 0] = 1
    target[:, :, 1] = 0
    MetricTester().run_class_metric_test(
        ddp=ddp,
        preds=preds,
        target=target,
        metric_class=cls,
        sk_metric=lambda p, t: sk_fn(t, p),
        metric_args={},
    )


def test_calibration_eager_jit_agree_on_logits():
    """Regression: logit normalization must be identical eager vs jitted."""
    import jax

    logits = jnp.asarray(_rng.standard_normal(200) * 3, dtype=jnp.float32)
    target = jnp.asarray(_rng.integers(0, 2, 200))
    eager = calibration_error(logits, target)
    jitted = jax.jit(calibration_error)(logits, target)
    np.testing.assert_allclose(np.asarray(eager), np.asarray(jitted), atol=1e-6)


# --------------------------------------------------------------------------- #
# KLDivergence option surface: log_prob x reduction (reference
# kl_divergence.py:81-123) vs a scipy rel_entr oracle
# --------------------------------------------------------------------------- #
@pytest.mark.parametrize("log_prob", [False, True], ids=["probs", "log-probs"])
@pytest.mark.parametrize("reduction", ["mean", "sum", "none"])
def test_kl_option_surface(log_prob, reduction):
    rng = np.random.default_rng(41)
    p = rng.dirichlet(np.ones(5), size=12).astype(np.float32)
    q = rng.dirichlet(np.ones(5), size=12).astype(np.float32)
    per_sample = rel_entr(p, q).sum(axis=-1)
    want = {"mean": per_sample.mean(), "sum": per_sample.sum(), "none": per_sample}[reduction]

    args = (np.log(p), np.log(q)) if log_prob else (p, q)
    got = kl_divergence(jnp.asarray(args[0]), jnp.asarray(args[1]),
                        log_prob=log_prob, reduction=reduction)
    np.testing.assert_allclose(np.asarray(got, np.float64), want, atol=1e-5)


def test_kl_class_log_prob_accumulates():
    rng = np.random.default_rng(42)
    p = rng.dirichlet(np.ones(4), size=16).astype(np.float32)
    q = rng.dirichlet(np.ones(4), size=16).astype(np.float32)
    m = KLDivergence(log_prob=True)
    m.update(jnp.asarray(np.log(p[:8])), jnp.asarray(np.log(q[:8])))
    m.update(jnp.asarray(np.log(p[8:])), jnp.asarray(np.log(q[8:])))
    want = rel_entr(p, q).sum(axis=-1).mean()
    np.testing.assert_allclose(float(m.compute()), want, atol=1e-5)
