"""StatScores root functional: reduce × mdmc_reduce × top_k × ignore_index grid.

The whole stat-scores-derived family (precision/recall/F-beta/specificity/
accuracy) consumes the counts this functional produces, so the reference
pins the raw [tp, fp, tn, fn, support] tensors themselves across its full
option grid (tests/classification/test_stat_scores.py:112-230 with the
mdmc fixtures). Same here, against a from-scratch numpy k-hot counter, plus
the Accuracy-specific ``subset_accuracy`` × mdmc cells.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.classification import Accuracy, StatScores
from metrics_tpu.ops.classification import accuracy, stat_scores
from tests.classification.inputs import (
    _input_multiclass,
    _input_multiclass_prob,
    _input_multidim_multiclass,
    _input_multidim_multiclass_prob,
    _input_multilabel_prob,
)
from tests.classification.khot_oracle import khot_rows, onehot_rows
from tests.helpers.testers import NUM_CLASSES, THRESHOLD, MetricTester

_t = MetricTester()


# --------------------------------------------------------------------------- #
# numpy oracle: k-hot counts with reference column-drop / sentinel semantics
# --------------------------------------------------------------------------- #
def _rows(preds, target, top_k):
    """Canonicalize one flat block to (M, C) k-hot / one-hot matrices."""
    return khot_rows(preds, top_k, NUM_CLASSES), onehot_rows(target, NUM_CLASSES)


def _np_counts(kh, oh, reduce, ignore_index):
    if ignore_index is not None and reduce != "macro":
        kh = np.delete(kh, ignore_index, axis=1)
        oh = np.delete(oh, ignore_index, axis=1)
    axis = 1 if reduce == "samples" else 0
    tp = (kh & oh).sum(axis)
    fp = (kh & (1 - oh)).sum(axis)
    fn = ((1 - kh) & oh).sum(axis)
    tn = ((1 - kh) & (1 - oh)).sum(axis)
    if reduce == "micro":
        tp, fp, tn, fn = tp.sum(), fp.sum(), tn.sum(), fn.sum()
    stacked = np.stack([tp, fp, tn, fn, tp + fn], axis=-1).astype(np.int64)
    if ignore_index is not None and reduce == "macro":
        stacked[..., ignore_index, :] = -1
    return stacked


def _np_stat_scores(preds, target, reduce, mdmc_reduce, top_k, ignore_index):
    if preds.ndim >= 2 and not (preds.ndim == 2 and np.issubdtype(preds.dtype, np.floating)):
        # multidim multiclass: (N, C, X) probs or (N, X) labels
        if np.issubdtype(preds.dtype, np.floating):
            per = [np.moveaxis(preds[n], 0, -1).reshape(-1, NUM_CLASSES) for n in range(preds.shape[0])]
        else:
            per = [preds[n].reshape(-1) for n in range(preds.shape[0])]
        tgt = [target[n].reshape(-1) for n in range(target.shape[0])]
        if mdmc_reduce == "global":
            p = np.concatenate(per) if per[0].ndim == 1 else np.vstack(per)
            kh, oh = _rows(p, np.concatenate(tgt), top_k)
            return _np_counts(kh, oh, reduce, ignore_index)
        blocks = []
        for p, t in zip(per, tgt):
            kh, oh = _rows(p, t, top_k)
            blocks.append(_np_counts(kh, oh, reduce, ignore_index))
        return np.stack(blocks)
    kh, oh = _rows(preds, target, top_k)
    return _np_counts(kh, oh, reduce, ignore_index)


_FLAT_CASES = [
    ("mc", _input_multiclass),
    ("mc_prob", _input_multiclass_prob),
]
_MDMC_CASES = [
    ("mdmc", _input_multidim_multiclass),
    ("mdmc_prob", _input_multidim_multiclass_prob),
]


@pytest.mark.parametrize("ignore_index", [None, 1])
@pytest.mark.parametrize("top_k", [None, 2])
@pytest.mark.parametrize("reduce", ["micro", "macro", "samples"])
@pytest.mark.parametrize("case,fix", _FLAT_CASES)
def test_stat_scores_flat_grid(case, fix, reduce, top_k, ignore_index):
    if top_k is not None and case == "mc":
        pytest.skip("top_k needs probability inputs")
    for i in range(fix.preds.shape[0]):
        got = stat_scores(
            jnp.asarray(fix.preds[i]), jnp.asarray(fix.target[i]),
            reduce=reduce, top_k=top_k, ignore_index=ignore_index, num_classes=NUM_CLASSES,
        )
        want = _np_stat_scores(fix.preds[i], fix.target[i], reduce, None, top_k, ignore_index)
        np.testing.assert_array_equal(np.asarray(got), want, err_msg=f"{case} {reduce}")


@pytest.mark.parametrize("ignore_index", [None, 1])
@pytest.mark.parametrize("top_k", [None, 2])
@pytest.mark.parametrize("mdmc_reduce", ["global", "samplewise"])
@pytest.mark.parametrize("reduce", ["micro", "macro", "samples"])
@pytest.mark.parametrize("case,fix", _MDMC_CASES)
def test_stat_scores_mdmc_grid(case, fix, reduce, mdmc_reduce, top_k, ignore_index):
    if top_k is not None and case == "mdmc":
        pytest.skip("top_k needs probability inputs")
    for i in range(fix.preds.shape[0]):
        got = stat_scores(
            jnp.asarray(fix.preds[i]), jnp.asarray(fix.target[i]),
            reduce=reduce, mdmc_reduce=mdmc_reduce, top_k=top_k,
            ignore_index=ignore_index, num_classes=NUM_CLASSES,
        )
        want = _np_stat_scores(fix.preds[i], fix.target[i], reduce, mdmc_reduce, top_k, ignore_index)
        np.testing.assert_array_equal(np.asarray(got), want, err_msg=f"{case} {reduce} {mdmc_reduce}")


@pytest.mark.parametrize("ddp", [False, True])
@pytest.mark.parametrize("reduce", ["micro", "macro"])
def test_stat_scores_class_ddp(ddp, reduce):
    """Class StatScores: summed counts across batches and ranks."""
    fix = _input_multiclass_prob
    _t.run_class_metric_test(
        ddp=ddp,
        preds=fix.preds,
        target=fix.target,
        metric_class=StatScores,
        sk_metric=lambda p, t: _np_stat_scores(p, t, reduce, None, 2, 1),
        metric_args={"reduce": reduce, "top_k": 2, "ignore_index": 1, "num_classes": NUM_CLASSES},
    )


# --------------------------------------------------------------------------- #
# Accuracy: subset_accuracy × mdmc × top_k cells (reference test_accuracy.py)
# --------------------------------------------------------------------------- #
def _np_accuracy_topk(preds_prob, target, k):
    """Sample counts as correct when the target class is in the top-k."""
    top = np.argsort(-preds_prob, axis=-1, kind="stable")[..., :k]
    return float(np.mean((top == target[..., None]).any(-1)))


@pytest.mark.parametrize("top_k", [1, 2, 3])
def test_accuracy_topk_vs_oracle(top_k):
    fix = _input_multiclass_prob
    for i in range(fix.preds.shape[0]):
        got = accuracy(jnp.asarray(fix.preds[i]), jnp.asarray(fix.target[i]), top_k=top_k)
        want = _np_accuracy_topk(fix.preds[i], fix.target[i], top_k)
        np.testing.assert_allclose(float(got), want, atol=1e-6)


@pytest.mark.parametrize("mdmc_average", ["global", "samplewise"])
@pytest.mark.parametrize("subset", [False, True])
def test_accuracy_mdmc_subset_cells(mdmc_average, subset):
    """subset_accuracy on mdmc inputs: a sample is one OUTER row under both
    mdmc_average values (subset_accuracy treats the extra dim jointly, so the
    reference yields the same all-elements-match row score either way), and it
    is correct iff ALL its element predictions match."""
    fix = _input_multidim_multiclass
    for i in range(fix.preds.shape[0]):
        p, t = fix.preds[i], fix.target[i]
        got = float(
            accuracy(
                jnp.asarray(p), jnp.asarray(t),
                mdmc_average=mdmc_average, subset_accuracy=subset, num_classes=NUM_CLASSES,
            )
        )
        if subset:
            # reference semantics: subset accuracy over mdmc treats the extra
            # dim jointly — every element of the sample must match
            want = float(np.mean((p == t).all(axis=-1)))
        elif mdmc_average == "global":
            want = float(np.mean(p == t))
        else:
            want = float(np.mean((p == t).mean(axis=-1)))
        np.testing.assert_allclose(got, want, atol=1e-6, err_msg=f"{mdmc_average} subset={subset}")


@pytest.mark.parametrize("ddp", [False, True])
@pytest.mark.parametrize("subset", [False, True])
def test_accuracy_multilabel_subset_class_ddp(ddp, subset):
    """Multilabel (threshold) accuracy, exact-match vs per-label, under ddp."""
    fix = _input_multilabel_prob

    def oracle(p, t):
        hard = (p >= THRESHOLD).astype(np.int64)
        if subset:
            return float(np.mean((hard == t).all(axis=-1)))
        return float(np.mean(hard == t))

    _t.run_class_metric_test(
        ddp=ddp,
        preds=fix.preds,
        target=fix.target,
        metric_class=Accuracy,
        sk_metric=oracle,
        metric_args={"subset_accuracy": subset, "threshold": THRESHOLD},
    )
