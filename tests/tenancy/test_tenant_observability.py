"""Tenancy observability: tracer events and Prometheus tenant series.

Pins the ISSUE-11 observability contract: every tenant lifecycle operation
emits a `tenancy/*` trace event with owner/bucket context, and the instrument
registry exports `metrics_tpu_tenant_*` series — including the per-tenant
label dimension on `metrics_tpu_tenant_updates_total` — in strictly parseable
exposition format.
"""

import jax.numpy as jnp
import numpy as np

import metrics_tpu as mt
from metrics_tpu import observability as obs
from metrics_tpu.core.metric import Metric
from metrics_tpu.observability.instruments import InstrumentRegistry
from tests.observability.test_exporters import _StrictPromParser


class TinyMean(Metric):
    full_state_update = False

    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("total", default=jnp.zeros((), jnp.float32), dist_reduce_fx="sum")
        self.add_state("count", default=jnp.zeros((), jnp.float32), dist_reduce_fx="sum")

    def update(self, values):
        self.total = self.total + jnp.sum(values)
        self.count = self.count + float(np.prod(values.shape))

    def compute(self):
        return self.total / jnp.maximum(self.count, 1.0)


def _exercised_set(name=None):
    ts = mt.TenantSet(
        mt.MetricCollection({"mean": TinyMean()}), capacity=8, name=name
    )
    for tid in ("a", "b", "c"):
        ts.admit(tid)
    ts.update(["a", "b", "c"], jnp.ones((3, 4), jnp.float32))
    ts.update(["a", "b"], jnp.ones((2, 4), jnp.float32))
    ts.compute()
    ts.reset(["c"])
    ts.evict("c")
    return ts


class TestTracer:
    def test_lifecycle_emits_tenancy_events(self):
        with obs.trace() as tracer:
            _exercised_set()
            counts = tracer.counts_by_name()
        assert counts["tenancy/admit"] == 3
        assert counts["tenancy/dispatch"] == 2
        assert counts["tenancy/compute"] == 1
        assert counts["tenancy/reset"] == 1
        assert counts["tenancy/evict"] == 1

    def test_dispatch_event_carries_bucket_context(self):
        with obs.trace() as tracer:
            ts = _exercised_set(name="svc")
            events = [e for e in tracer.events() if e.name == "tenancy/dispatch"]
        assert len(events) == 2
        for ev, (k, bucket) in zip(events, ((3, 4), (2, 2))):
            assert ev.args["owner"] == ts.name == "svc"
            assert ev.args["tenants"] == k
            assert ev.args["bucket"] == bucket  # exact pow2: 3 -> 4, 2 -> 2

    def test_disabled_tracer_emits_nothing(self):
        _exercised_set()
        with obs.trace() as tracer:
            counts = tracer.counts_by_name()
        assert not any(n.startswith("tenancy/") for n in counts)


class TestPrometheus:
    def test_tenant_series_parse_strictly(self):
        reg = InstrumentRegistry()
        ts = _exercised_set(name="svc")
        reg.register_tenant_set(ts)
        text = obs.to_prometheus_text(reg)
        families, samples = _StrictPromParser().parse(text)

        by_name = {}
        for name, labels, value in samples:
            by_name.setdefault(name, []).append((labels, value))

        gauges = {
            "metrics_tpu_tenant_active": 2.0,  # c was evicted
            "metrics_tpu_tenant_capacity": 8.0,
            "metrics_tpu_tenant_bucket_width": 2.0,  # last dispatch was k=2
            "metrics_tpu_tenant_executables": float(ts.stats.compiles),
        }
        for name, expect in gauges.items():
            assert families[name]["type"] == "gauge"
            (labels, value), = by_name[name]
            assert labels == {"owner": "svc"}
            assert value == expect

        counters = {
            "metrics_tpu_tenant_admits_total": 3.0,
            "metrics_tpu_tenant_evicts_total": 1.0,
            "metrics_tpu_tenant_resets_total": 1.0,
            "metrics_tpu_tenant_dispatches_total": 2.0,
        }
        for name, expect in counters.items():
            assert families[name]["type"] == "counter"
            (labels, value), = by_name[name]
            assert labels == {"owner": "svc"}
            assert value == expect

    def test_per_tenant_update_label_dimension(self):
        reg = InstrumentRegistry()
        ts = _exercised_set(name="svc")
        reg.register_tenant_set(ts)
        _, samples = _StrictPromParser().parse(obs.to_prometheus_text(reg))
        updates = {
            labels["tenant"]: value
            for name, labels, value in samples
            if name == "metrics_tpu_tenant_updates_total"
        }
        # only ACTIVE tenants get a series; the evicted c disappears
        assert updates == {"a": 2.0, "b": 2.0}

    def test_dead_set_drops_out_of_exposition(self):
        reg = InstrumentRegistry()
        ts = _exercised_set(name="svc")
        reg.register_tenant_set(ts)
        assert "metrics_tpu_tenant_active" in obs.to_prometheus_text(reg)
        del ts  # weakref registration: a collected set leaves no stale series
        import gc

        gc.collect()
        assert "metrics_tpu_tenant_active" not in obs.to_prometheus_text(reg)
