"""Tenant-batched bucketed sync: collective-count independence and parity.

Pins the ISSUE-11 sync contract: a TenantSet's cross-device sync folds the
tenant axis into the flat (reduction, dtype) buckets, so the collective count
per sync is independent of capacity N and of the number of stacked groups —
and the synced values match a per-leaf tree_map of the reduction exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu as mt
from metrics_tpu.core.metric import Metric
from metrics_tpu.parallel import count_collectives
from metrics_tpu.parallel.sync import sync_stacked_states


class TinyMean(Metric):
    full_state_update = False

    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("total", default=jnp.zeros((), jnp.float32), dist_reduce_fx="sum")
        self.add_state("count", default=jnp.zeros((), jnp.float32), dist_reduce_fx="sum")

    def update(self, values):
        self.total = self.total + jnp.sum(values)
        self.count = self.count + float(np.prod(values.shape))

    def compute(self):
        return self.total / jnp.maximum(self.count, 1.0)


class TinyMax(Metric):
    full_state_update = False

    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("peak", default=jnp.full((), -jnp.inf), dist_reduce_fx="max")

    def update(self, values):
        self.peak = jnp.maximum(self.peak, jnp.max(values))

    def compute(self):
        return self.peak


def _tenant_set(capacity, n_admit):
    ts = mt.TenantSet(
        mt.MetricCollection({"mean": TinyMean(), "mx": TinyMax()}),
        capacity=capacity,
    )
    ids = [f"t{i}" for i in range(n_admit)]
    for tid in ids:
        ts.admit(tid)
    ts.update(ids, jnp.arange(n_admit * 4, dtype=jnp.float32).reshape(n_admit, 4))
    return ts, ids


def _count(ts):
    with count_collectives() as box:
        jax.make_jaxpr(
            lambda st: ts.sync_states(st, "data"), axis_env=[("data", 8)]
        )(ts.stacked_states)
    return box


class TestCollectiveCount:
    def test_count_independent_of_capacity(self):
        small, _ = _tenant_set(16, 3)
        large, _ = _tenant_set(1024, 37)
        b_small, b_large = _count(small), _count(large)
        # one (sum, f32) bucket + one (max, f32) bucket, regardless of N
        assert b_small["count"] == b_large["count"] == 2
        assert b_small["by_kind"] == b_large["by_kind"]

    def test_count_independent_of_group_count(self):
        one = mt.TenantSet(mt.MetricCollection({"mean": TinyMean()}), capacity=16)
        one.admit("a")
        one.update(["a"], jnp.ones((1, 4), jnp.float32))
        two, _ = _tenant_set(16, 1)
        # TinyMax adds a max bucket; TinyMean's two sum leaves share ONE bucket
        assert _count(one)["count"] == 1
        assert _count(two)["count"] == 2

    def test_payload_scales_with_capacity(self):
        small, _ = _tenant_set(16, 3)
        large, _ = _tenant_set(1024, 37)
        b_small, b_large = _count(small), _count(large)
        assert b_large["bytes"] == b_small["bytes"] * (1024 // 16)


class TestNumericParity:
    def test_pmap_sum_and_max_parity(self):
        n_dev = jax.local_device_count()
        assert n_dev == 8  # pinned by tests/conftest.py's XLA flag
        ts, _ = _tenant_set(8, 5)
        base = ts.stacked_states
        # distinct per-device replicas: device d holds base * (d + 1)
        dev_stacked = jax.tree_util.tree_map(
            lambda v: jnp.stack([v * (d + 1.0) for d in range(n_dev)]), base
        )
        synced = jax.pmap(
            lambda st: ts.sync_states(st, "data"), axis_name="data"
        )(dev_stacked)
        scale = float(sum(range(1, n_dev + 1)))
        for lname, st in base.items():
            for name, leaf in st.items():
                got = np.asarray(synced[lname][name])
                ref = np.asarray(leaf)
                if name == "peak":
                    expect = ref * n_dev  # max over d of ref*(d+1)
                else:
                    expect = ref * scale
                for d in range(n_dev):
                    np.testing.assert_array_equal(got[d], expect)

    def test_no_axis_is_identity(self):
        ts, _ = _tenant_set(8, 3)
        synced = ts.sync_states(ts.stacked_states, None)
        for lname, st in ts.stacked_states.items():
            for name, leaf in st.items():
                np.testing.assert_array_equal(
                    np.asarray(synced[lname][name]), np.asarray(leaf)
                )


class TestTransports:
    """Quantized transports fold into the stacked buckets the same way: the
    collective count per transport stays independent of capacity N, and a
    per-state declaration on the template reaches the stacked sync."""

    def _count_with(self, capacity, n_admit, transport):
        ts, _ = _tenant_set(capacity, n_admit)
        reductions = {
            lname: {n: ts.template._metrics[lname]._reductions[n] for n in st}
            for lname, st in ts.stacked_states.items()
        }
        transports = {"mean": {"total": transport, "count": transport}}
        with count_collectives() as box:
            jax.make_jaxpr(
                lambda st: sync_stacked_states(
                    st, reductions, "data", transports=transports
                ),
                axis_env=[("data", 8)],
            )(ts.stacked_states)
        return box

    @pytest.mark.parametrize("transport", ["bf16", "int8"])
    def test_count_independent_of_capacity_per_transport(self, transport):
        b_small = self._count_with(16, 3, transport)
        b_large = self._count_with(1024, 37, transport)
        assert b_small["count"] == b_large["count"]
        assert b_small["by_kind"] == b_large["by_kind"]
        assert transport in b_small["bytes_by_transport"]
        # quantized wire bytes still scale with N, at the reduced width
        small_w = b_small["bytes_by_transport"][transport]["wire"]
        large_w = b_large["bytes_by_transport"][transport]["wire"]
        assert large_w > small_w

    def test_template_declaration_reaches_stacked_sync(self):
        class DeclaredMean(TinyMean):
            def __init__(self, **kw):
                Metric.__init__(self, **kw)
                self.add_state("total", default=jnp.zeros((), jnp.float32),
                               dist_reduce_fx="sum", sync_transport="bf16")
                self.add_state("count", default=jnp.zeros((), jnp.float32),
                               dist_reduce_fx="sum", sync_transport="bf16")

        ts = mt.TenantSet(
            mt.MetricCollection({"mean": DeclaredMean(), "mx": TinyMax()}),
            capacity=16,
        )
        ts.admit("a")
        ts.update(["a"], jnp.ones((1, 4), jnp.float32))
        with count_collectives() as box:
            jax.make_jaxpr(
                lambda st: ts.sync_states(st, "data"), axis_env=[("data", 8)]
            )(ts.stacked_states)
        assert "bf16" in box["bytes_by_transport"]
        bf16 = box["bytes_by_transport"]["bf16"]
        assert bf16["wire"] * 2 == bf16["logical"]
        assert box["refusals"] == []


class TestErrors:
    def test_non_elementwise_reduction_raises(self):
        states = {"m": {"buf": jnp.zeros((4, 2), jnp.float32)}}
        reductions = {"m": {"buf": "cat"}}

        def trace():
            jax.make_jaxpr(
                lambda st: sync_stacked_states(st, reductions, "data"),
                axis_env=[("data", 8)],
            )(states)

        with pytest.raises(ValueError, match="non-elementwise"):
            trace()


class TestIncrementalStacked:
    """ISSUE-15: the stacked incremental carry keeps the tenant axis folded
    into the flat buckets — per-emission collective count independent of N,
    finalize bitwise-equal to the deferred sync_states over the same states,
    and a zero-collective finalize when the cadence divides the streak."""

    def _emission_count(self, capacity, n_admit):
        ts, _ = _tenant_set(capacity, n_admit)
        carry = ts.init_incremental_sync(ts.stacked_states)
        with count_collectives() as box:
            jax.make_jaxpr(
                lambda st: ts.advance_incremental_sync(carry, st, "data").acc,
                axis_env=[("data", 8)],
            )(ts.stacked_states)
        return box

    def test_emission_count_independent_of_capacity(self):
        b_small = self._emission_count(16, 3)
        b_large = self._emission_count(1024, 37)
        # one (sum, f32) bucket + one (max, f32) bucket per emission, any N
        assert b_small["count"] == b_large["count"] == 2
        assert b_small["by_kind"] == b_large["by_kind"]

    def test_finalize_after_emission_is_collective_free(self):
        ts, _ = _tenant_set(16, 3)

        def streak(st):
            carry = ts.init_incremental_sync(st)
            carry = ts.advance_incremental_sync(carry, st, "data")
            with count_collectives() as fin_box:
                ts.finalize_incremental_sync(carry, "data")
            boxes.append(fin_box["count"])
            return jnp.zeros(())

        boxes = []
        jax.make_jaxpr(streak, axis_env=[("data", 8)])(ts.stacked_states)
        assert boxes == [0]  # every bucket was already emitted in-streak

    def test_pmap_parity_with_deferred_sync(self):
        n_dev = jax.local_device_count()
        assert n_dev == 8
        ts, _ = _tenant_set(8, 5)
        base = ts.stacked_states
        dev_stacked = jax.tree_util.tree_map(
            lambda v: jnp.stack([v * (d + 1.0) for d in range(n_dev)]), base
        )

        def run_incr(st):
            carry = ts.init_incremental_sync(st)
            carry = ts.advance_incremental_sync(carry, st, "data")
            return ts.finalize_incremental_sync(carry, "data")

        got = jax.pmap(run_incr, axis_name="data")(dev_stacked)
        ref = jax.pmap(
            lambda st: ts.sync_states(st, "data"), axis_name="data"
        )(dev_stacked)
        for lname, st in ref.items():
            for name, leaf in st.items():
                np.testing.assert_array_equal(
                    np.asarray(got[lname][name]), np.asarray(leaf)
                )
