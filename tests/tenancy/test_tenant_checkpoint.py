"""TenantSet checkpointing: one snapshot for all tenants, typed refusals.

Pins the ISSUE-11 checkpoint contract: a TenantSet saves its whole slot table
in one shard, restores bitwise with per-tenant update counts intact, and
refuses — with actionable errors — the two cases that cannot round-trip:
eager compute groups (analysis rule E110) and a changed world size (tenant
slots are host-local; move tenants with export_tenant/import_tenant).
"""

import os

import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu as mt
from metrics_tpu.checkpoint import (
    CheckpointCorruptError,
    CheckpointMismatchError,
    available_steps,
    restore_checkpoint,
    save_checkpoint,
    verify_checkpoint,
)
from metrics_tpu.checkpoint import io as _io
from metrics_tpu.core.metric import Metric
from metrics_tpu.utils.exceptions import MetricsUserError


class TinyMean(Metric):
    full_state_update = False

    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("total", default=jnp.zeros((), jnp.float32), dist_reduce_fx="sum")
        self.add_state("count", default=jnp.zeros((), jnp.float32), dist_reduce_fx="sum")

    def update(self, values):
        self.total = self.total + jnp.sum(values)
        self.count = self.count + float(np.prod(values.shape))

    def compute(self):
        return self.total / jnp.maximum(self.count, 1.0)


def _populated_set(capacity=8):
    ts = mt.TenantSet(mt.MetricCollection({"mean": TinyMean()}), capacity=capacity)
    for tid in ("a", "b", "c"):
        ts.admit(tid)
    ts.update(["a", "b", "c"], jnp.arange(12, dtype=jnp.float32).reshape(3, 4))
    ts.update(["b"], jnp.full((1, 4), 100.0, jnp.float32))
    return ts


def _fresh_like(capacity=8):
    ts = mt.TenantSet(mt.MetricCollection({"mean": TinyMean()}), capacity=capacity)
    for tid in ("a", "b", "c"):
        ts.admit(tid)
    return ts


class TestRoundTrip:
    def test_save_verify_restore_parity(self, tmp_path):
        root = str(tmp_path / "ckpt")
        ts = _populated_set()
        before = ts.compute()
        save_checkpoint(ts, root, world_size=1, shard_index=0)

        report = verify_checkpoint(root)
        assert report.ok

        fresh = _fresh_like()
        info = restore_checkpoint(fresh, root, host_count=1)
        assert info.fallback_from is None
        after = fresh.compute()
        for tid in ("a", "b", "c"):
            np.testing.assert_array_equal(
                np.asarray(before[tid]["mean"]), np.asarray(after[tid]["mean"])
            )
        assert fresh.tenant_update_counts() == ts.tenant_update_counts()
        assert fresh.tenant_ids() == ts.tenant_ids()

    def test_restore_does_not_perturb_executable_cache(self, tmp_path):
        """A restored stacked pytree has the same abstract signature as a live
        one, so the next dispatch at a warmed width is a cache hit."""
        root = str(tmp_path / "ckpt")
        save_checkpoint(_populated_set(), root, world_size=1, shard_index=0)
        fresh = _fresh_like()
        fresh.update(["a", "b", "c"], jnp.ones((3, 4), jnp.float32))  # warm width 4
        compiles = fresh.stats.compiles
        restore_checkpoint(fresh, root, host_count=1)
        fresh.update(["a", "b", "c"], jnp.ones((3, 4), jnp.float32))
        assert fresh.stats.compiles == compiles
        assert fresh.stats.cache_hits >= 1

    def test_fallback_to_older_verifiable_step(self, tmp_path):
        root = str(tmp_path / "ckpt")
        ts = _populated_set()
        save_checkpoint(ts, root, world_size=1, shard_index=0)
        good = ts.compute()
        ts.update(["a"], jnp.full((1, 4), 7.0, jnp.float32))
        save_checkpoint(ts, root, world_size=1, shard_index=0)
        # tear the newest step's payload
        bad_step = available_steps(root)[-1]
        sdir = _io.step_dir(root, bad_step)
        npz = next(n for n in os.listdir(sdir) if n.endswith(".npz"))
        path = os.path.join(sdir, npz)
        data = bytearray(open(path, "rb").read())
        data[len(data) // 2] ^= 0xFF
        with open(path, "wb") as fh:
            fh.write(bytes(data))

        fresh = _fresh_like()
        with pytest.warns(UserWarning, match="fall"):
            info = restore_checkpoint(fresh, root, host_count=1)
        assert info.fallback_from == bad_step
        assert info.step == available_steps(root)[0]
        after = fresh.compute()
        for tid in ("a", "b", "c"):
            np.testing.assert_array_equal(
                np.asarray(good[tid]["mean"]), np.asarray(after[tid]["mean"])
            )

    def test_explicit_corrupt_step_raises(self, tmp_path):
        root = str(tmp_path / "ckpt")
        save_checkpoint(_populated_set(), root, world_size=1, shard_index=0)
        bad_step = available_steps(root)[-1]
        sdir = _io.step_dir(root, bad_step)
        npz = next(n for n in os.listdir(sdir) if n.endswith(".npz"))
        path = os.path.join(sdir, npz)
        data = bytearray(open(path, "rb").read())
        data[len(data) // 2] ^= 0xFF
        with open(path, "wb") as fh:
            fh.write(bytes(data))
        with pytest.raises(CheckpointCorruptError):
            restore_checkpoint(_fresh_like(), root, step=bad_step, host_count=1)


class TestRefusals:
    def test_capacity_mismatch_is_fingerprint_error(self, tmp_path):
        root = str(tmp_path / "ckpt")
        save_checkpoint(_populated_set(capacity=8), root, world_size=1, shard_index=0)
        with pytest.raises(CheckpointMismatchError):
            restore_checkpoint(_fresh_like(capacity=16), root, host_count=1)

    def test_world_size_change_refused_with_migration_hint(self, tmp_path):
        root = str(tmp_path / "ckpt")
        save_checkpoint(_populated_set(), root, world_size=1, shard_index=0)
        with pytest.raises(CheckpointMismatchError, match="export_tenant"):
            restore_checkpoint(_fresh_like(), root, host_count=2, host_index=0)

    def test_eager_group_refuses_to_save(self, tmp_path):
        ts = mt.TenantSet(
            mt.MetricCollection({"mean": TinyMean(), "cat": mt.CatMetric()}),
            capacity=4,
        )
        ts.admit("a")
        ts.update(["a"], jnp.ones((1, 4), jnp.float32))
        with pytest.raises(MetricsUserError, match="E110"):
            save_checkpoint(ts, str(tmp_path / "ckpt"), world_size=1, shard_index=0)
