"""Chaos injection at the tenancy boundaries: faults fire BEFORE mutation.

Pins the ISSUE-11 fault contract: each tenancy site (`tenancy/dispatch`,
`tenancy/admit`, `tenancy/evict`) is injectable via the deterministic chaos
harness, a fired fault leaves NO partial state (occupancy and per-tenant
update counts unchanged), and the interrupted operation succeeds on retry.
"""

import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu as mt
from metrics_tpu.core.metric import Metric
from metrics_tpu.resilience import FaultSpec
from metrics_tpu.resilience import chaos
from metrics_tpu.resilience.chaos import ChaosError, KNOWN_SITES


class TinyMean(Metric):
    full_state_update = False

    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("total", default=jnp.zeros((), jnp.float32), dist_reduce_fx="sum")
        self.add_state("count", default=jnp.zeros((), jnp.float32), dist_reduce_fx="sum")

    def update(self, values):
        self.total = self.total + jnp.sum(values)
        self.count = self.count + float(np.prod(values.shape))

    def compute(self):
        return self.total / jnp.maximum(self.count, 1.0)


def _ts(n_admit=2):
    ts = mt.TenantSet(mt.MetricCollection({"mean": TinyMean()}), capacity=4)
    for i in range(n_admit):
        ts.admit(f"t{i}")
    return ts


def test_tenancy_sites_are_registered():
    for site in ("tenancy/dispatch", "tenancy/admit", "tenancy/evict"):
        assert site in KNOWN_SITES


def test_dispatch_fault_leaves_no_partial_state():
    ts = _ts()
    ts.update(["t0", "t1"], jnp.ones((2, 4), jnp.float32))
    counts = dict(ts.tenant_update_counts())
    before = {t: np.asarray(v["mean"]) for t, v in ts.compute().items()}
    with chaos.plan([FaultSpec("tenancy/dispatch", nth=1, times=1)], seed=0) as p:
        with pytest.raises(ChaosError):
            ts.update(["t0", "t1"], jnp.full((2, 4), 9.0, jnp.float32))
        assert p.fired("tenancy/dispatch") == 1
        assert ts.tenant_update_counts() == counts
        after = {t: np.asarray(v["mean"]) for t, v in ts.compute().items()}
        for t in before:
            np.testing.assert_array_equal(before[t], after[t])
        # the plan's budget is spent — the retry goes through
        ts.update(["t0", "t1"], jnp.full((2, 4), 9.0, jnp.float32))
    assert ts.tenant_update_counts()["t0"] == counts["t0"] + 1


def test_admit_fault_leaves_no_slot_assigned():
    ts = _ts()
    with chaos.plan([FaultSpec("tenancy/admit", nth=1, times=1)], seed=0) as p:
        with pytest.raises(ChaosError):
            ts.admit("t9")
        assert p.fired("tenancy/admit") == 1
        assert ts.active_count == 2
        assert "t9" not in ts.tenant_ids()
        ts.admit("t9")  # retry succeeds
    assert "t9" in ts.tenant_ids()
    assert ts.active_count == 3


def test_evict_fault_keeps_tenant_state():
    ts = _ts()
    ts.update(["t0", "t1"], jnp.ones((2, 4), jnp.float32))
    before = np.asarray(ts.compute(["t1"])["t1"]["mean"])
    with chaos.plan([FaultSpec("tenancy/evict", nth=1, times=1)], seed=0) as p:
        with pytest.raises(ChaosError):
            ts.evict("t1")
        assert p.fired("tenancy/evict") == 1
        assert "t1" in ts.tenant_ids()
        np.testing.assert_array_equal(
            np.asarray(ts.compute(["t1"])["t1"]["mean"]), before
        )
        ts.evict("t1")  # retry succeeds
    assert "t1" not in ts.tenant_ids()
    assert ts.active_count == 1


def test_nth_dispatch_fault_is_deterministic():
    """nth=3 means exactly the third dispatch fails — replayable by seed."""
    for _ in range(2):
        ts = _ts()
        with chaos.plan([FaultSpec("tenancy/dispatch", nth=3, times=1)], seed=7):
            ts.update(["t0"], jnp.ones((1, 4), jnp.float32))
            ts.update(["t0"], jnp.ones((1, 4), jnp.float32))
            with pytest.raises(ChaosError):
                ts.update(["t0"], jnp.ones((1, 4), jnp.float32))
        assert ts.tenant_update_counts()["t0"] == 2
