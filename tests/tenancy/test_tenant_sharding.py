"""Tenancy × sharding interplay: a mesh-placed sharded member must never be
silently stacked — the tenant leading axis would fight the placement.

Pinned here:

* ``classify_tenant_member`` demotes a ``shard_state``-placed metric with the
  engine's stable reason string; an *unplaced* ``shard_axis`` declaration is
  inert and still stacks;
* a TenantSet whose template carries a placed sharded member runs that
  member's group as per-tenant eager clones (reason surfaced in
  ``partition_view``) and stays bitwise-correct against independent
  replicated references, while unrelated groups keep the stacked path;
* the analyzer's E110 finding names the demotion in its extras
  (``tenant_reason``) when sharding is what demotes.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import metrics_tpu as mt
from metrics_tpu import ConfusionMatrix
from metrics_tpu.core.engine import PATH_EAGER, PATH_TENANT, classify_tenant_member
from metrics_tpu.core.metric import Metric
from metrics_tpu.parallel import make_mesh

WORLD = 8
C = 8

DEMOTION_REASON = "sharded state: the tenant axis would conflict with the mesh placement"


@pytest.fixture()
def mesh():
    devices = jax.devices()
    if len(devices) < WORLD:
        pytest.skip("needs 8 devices")
    return make_mesh([WORLD], ["data"], devices[:WORLD])


class ShardedCounts(Metric):
    """Dense class-sharded counts: tenant-stackable until a placement lands."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state(
            "counts", default=jnp.zeros((C,), jnp.int32), dist_reduce_fx="sum", shard_axis=0
        )

    def update(self, labels, *_):
        self.counts = self.counts + jnp.bincount(labels, length=C).astype(jnp.int32)

    def compute(self):
        return self.counts.sum()


def _labels(seed, n=32):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, C, size=(n,)), jnp.int32)


# --------------------------------------------------------------- classifier --
def test_unplaced_shard_axis_declaration_still_stacks():
    # the declaration alone is inert (no placement => leaves are replicas)
    path, reason = classify_tenant_member(ShardedCounts())
    assert path == PATH_TENANT, reason
    assert ConfusionMatrix(num_classes=C).shard_axes == {"confmat": 0}
    path, _ = classify_tenant_member(ConfusionMatrix(num_classes=C))
    assert path == PATH_TENANT


@pytest.mark.mesh8
def test_placed_sharded_member_demotes_with_stable_reason(mesh):
    m = ConfusionMatrix(num_classes=C).shard_state(mesh)
    path, reason = classify_tenant_member(m)
    assert path == PATH_EAGER
    assert reason == DEMOTION_REASON


# ----------------------------------------------------------------- TenantSet --
@pytest.mark.mesh8
def test_tenant_set_demotes_sharded_group_and_stays_correct(mesh):
    template = mt.MetricCollection(
        {"cm": ConfusionMatrix(num_classes=C).shard_state(mesh), "counts": ShardedCounts()}
    )
    ts = mt.TenantSet(template, capacity=4)
    view = ts.partition_view()["tenant"]
    assert view["cm"]["path"] == PATH_EAGER
    assert DEMOTION_REASON in view["cm"]["reason"]
    # the unplaced member's group keeps the stacked path
    assert view["counts"]["path"] == PATH_TENANT

    tenants = ("a", "b", "c")
    for t in tenants:
        ts.admit(t)
    refs = {t: ConfusionMatrix(num_classes=C) for t in tenants}
    ref_counts = {t: ShardedCounts() for t in tenants}
    for step in range(2):
        preds = jnp.stack([_labels(10 * step + i) for i in range(len(tenants))])
        target = jnp.stack([_labels(100 * step + i) for i in range(len(tenants))])
        ts.update(list(tenants), preds, target)
        for i, t in enumerate(tenants):
            refs[t].update(preds[i], target[i])
            ref_counts[t].update(preds[i])
    assert ts.stats.eager_tenant_updates > 0
    out = ts.compute(list(tenants))
    for t in tenants:
        assert np.array_equal(np.asarray(out[t]["cm"]), np.asarray(refs[t].compute()))
        assert np.array_equal(
            np.asarray(out[t]["counts"]), np.asarray(ref_counts[t].compute())
        )


# ------------------------------------------------------------------ analyzer --
def test_demotion_reason_named_in_E110_extras():
    from metrics_tpu.analysis.eval_stage import evaluate_entry
    from metrics_tpu.analysis.registry import Entry

    spec = {"inputs": [("int32", (32,))]}

    # no placement: no E110 at all
    findings = evaluate_entry(Entry(cls=ShardedCounts, spec=dict(spec)))
    assert "E110" not in {f.rule for f in findings}

    def _placed():
        m = ShardedCounts()
        # the analyzer's device-free stand-in for an active placement (the
        # same sentinel shape the E108 leg uses)
        m._state_sharding = ("__test__", "data")
        return m

    findings = evaluate_entry(Entry(cls=ShardedCounts, spec=dict(spec, init_fn=_placed)))
    e110 = [f for f in findings if f.rule == "E110"]
    assert len(e110) == 1
    assert e110[0].extra["tenant_path"] == PATH_EAGER
    assert e110[0].extra["tenant_reason"] == DEMOTION_REASON
