"""TenantSet core invariants (ISSUE 11): stacked dispatch bitwise parity vs
independent per-tenant streams across ragged occupancies, pow2 bucket
executable caching (occupancy churn never recompiles), masked-tenant state
immutability, zero-recompile reset/evict/admit pinned through the dispatcher's
``stable_hits`` counter, single-tenant export/import, and the user-error
surface (duplicate ids, unadmitted tenants, capacity, bad templates)."""
import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu as mt
from metrics_tpu.core.engine import PATH_EAGER, PATH_TENANT, classify_tenant_member
from metrics_tpu.core.metric import Metric
from metrics_tpu.utils.exceptions import MetricsUserError


class TinyMean(Metric):
    """Cheap dense-state metric so the 1024-tenant sweeps stay fast."""

    full_state_update = False

    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("total", default=jnp.zeros((), jnp.float32), dist_reduce_fx="sum")
        self.add_state("count", default=jnp.zeros((), jnp.float32), dist_reduce_fx="sum")

    def update(self, values):
        self.total = self.total + jnp.sum(values)
        self.count = self.count + float(np.prod(values.shape))

    def compute(self):
        return self.total / jnp.maximum(self.count, 1.0)


class TinyMax(Metric):
    full_state_update = False

    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("peak", default=jnp.full((), -jnp.inf, jnp.float32), dist_reduce_fx="max")

    def update(self, values):
        self.peak = jnp.maximum(self.peak, jnp.max(values))

    def compute(self):
        return self.peak


def _tiny_set(capacity, n_admit=None):
    ts = mt.TenantSet(
        mt.MetricCollection({"mean": TinyMean(), "mx": TinyMax()}), capacity=capacity
    )
    for i in range(n_admit if n_admit is not None else capacity):
        ts.admit(f"t{i}")
    return ts


# ----------------------------------------------------------- classification --
class TestClassification:
    def test_dense_elementwise_metric_stacks(self):
        path, reason = classify_tenant_member(TinyMean())
        assert path == PATH_TENANT and "stackable" in reason

    def test_catbuffer_metric_is_eager(self):
        path, reason = classify_tenant_member(mt.CatMetric())
        assert path == PATH_EAGER

    def test_partition_view_has_tenant_section(self):
        ts = _tiny_set(4)
        view = ts.partition_view()
        assert set(view["tenant"]) == {"mean", "mx"}
        assert all(info["path"] == PATH_TENANT for info in view["tenant"].values())

    def test_eager_member_reason_is_reported(self):
        ts = mt.TenantSet(
            mt.MetricCollection({"mean": TinyMean(), "cat": mt.CatMetric()}), capacity=2
        )
        info = ts.partition_view()["tenant"]["cat"]
        assert info["path"] == PATH_EAGER and info["reason"]


# ------------------------------------------------------------------- parity --
class TestOccupancyParity:
    CAP = 1024

    @pytest.mark.parametrize("k", [1, 37, 64, 1000])
    def test_ragged_occupancy_bitwise_parity(self, k):
        """k of 1024 active tenants: the stacked dispatch must be bit-for-bit
        identical to k independent pure-protocol streams."""
        ts = _tiny_set(self.CAP)
        ids = ts.tenant_ids()
        rng = np.random.default_rng(k)
        ref_mean, ref_max = TinyMean(), TinyMax()
        states = {}
        touched = set()
        for _ in range(2):
            sel = rng.choice(self.CAP, size=k, replace=False)
            vals = jnp.asarray(rng.normal(size=(k, 4)), jnp.float32)
            ts.update([ids[i] for i in sel], vals)
            for j, i in enumerate(sel):
                sm, sx = states.get(i, (ref_mean.init_state(), ref_max.init_state()))
                states[i] = (
                    ref_mean.update_state(sm, vals[j]),
                    ref_max.update_state(sx, vals[j]),
                )
                touched.add(int(i))
        out = ts.compute([ids[i] for i in sorted(touched)])
        for i in sorted(touched):
            got = out[ids[i]]
            assert np.array_equal(
                np.asarray(got["mean"]), np.asarray(ref_mean.compute_state(states[i][0]))
            )
            assert np.array_equal(
                np.asarray(got["mx"]), np.asarray(ref_max.compute_state(states[i][1]))
            )

    def test_real_collection_parity(self):
        """Accuracy + MSE through the stacked path vs stateful collections."""
        k, b, c = 3, 16, 4
        template = mt.MetricCollection(
            {"acc": mt.Accuracy(num_classes=c), "mse": mt.MeanSquaredError()}
        )
        ts = mt.TenantSet(template, capacity=8)
        refs = {
            f"t{i}": mt.MetricCollection(
                {"acc": mt.Accuracy(num_classes=c), "mse": mt.MeanSquaredError()}
            )
            for i in range(k)
        }
        for tid in refs:
            ts.admit(tid)
        rng = np.random.default_rng(0)
        for _ in range(3):
            preds = jnp.asarray(rng.integers(0, c, (k, b)), jnp.int32)
            target = jnp.asarray(rng.integers(0, c, (k, b)), jnp.int32)
            ts.update(list(refs), preds, target)
            for j, coll in enumerate(refs.values()):
                coll.update(preds[j], target[j])
        out = ts.compute()
        for tid, coll in refs.items():
            expect = coll.compute()
            assert set(out[tid]) == set(expect)
            for name in expect:
                assert np.array_equal(
                    np.asarray(out[tid][name]), np.asarray(expect[name])
                ), (tid, name)

    def test_batched_broadcast_and_static_leaves(self):
        """A ``(k,)``-leading array is per-tenant rows, other arrays broadcast
        to every tenant, python scalars are static config."""

        class Scaled(Metric):
            full_state_update = False

            def __init__(self, **kw):
                super().__init__(**kw)
                self.add_state("total", default=jnp.zeros((), jnp.float32), dist_reduce_fx="sum")

            def update(self, values, weight, gain):
                self.total = self.total + jnp.sum(values * weight) * gain

            def compute(self):
                return self.total

        ts = mt.TenantSet(mt.MetricCollection(Scaled()), capacity=4)
        ts.admit("a"); ts.admit("b")
        vals = jnp.asarray([[1.0, 2.0], [3.0, 4.0]], jnp.float32)  # per-tenant rows
        weight = jnp.asarray([2.0, 0.5], jnp.float32)  # shape (2,) == k: per-tenant
        ts.update(["a", "b"], vals, weight, 3.0)
        out = ts.compute()
        assert np.asarray(out["a"]["Scaled"]) == pytest.approx((1 + 2) * 2 * 3)
        assert np.asarray(out["b"]["Scaled"]) == pytest.approx((3 + 4) * 0.5 * 3)
        # broadcast leaf: shape (3,) != k, the same vector reaches both tenants
        w3 = jnp.asarray([1.0, 2.0, 3.0], jnp.float32)
        vals3 = jnp.asarray([[1.0, 1.0, 1.0], [2.0, 2.0, 2.0]], jnp.float32)
        ts.update(["a", "b"], vals3, w3, 1.0)
        out = ts.compute()
        assert np.asarray(out["a"]["Scaled"]) == pytest.approx((1 + 2) * 2 * 3 + 6)
        assert np.asarray(out["b"]["Scaled"]) == pytest.approx((3 + 4) * 0.5 * 3 + 12)


# ------------------------------------------------------ executable caching --
class TestBucketCaching:
    def test_one_executable_across_occupancy_churn(self):
        ts = _tiny_set(1024)
        ids = ts.tenant_ids()
        rng = np.random.default_rng(0)
        vals = jnp.asarray(rng.normal(size=(37, 4)), jnp.float32)
        ts.update(ids[:37], vals)
        assert ts.stats.compiles == 1
        for off in (1, 101, 500):  # different 37-subsets: same 64-wide bucket
            ts.update(ids[off : off + 37], vals)
        ts.update(ids[:33], vals[:33])  # 33 -> same pow2 bucket (64)
        assert ts.stats.compiles == 1
        assert ts.stats.cache_hits == 4
        assert ts.stats.last_bucket == 64

    def test_bucket_transition_compiles_once_per_width(self):
        ts = _tiny_set(64)
        ids = ts.tenant_ids()
        vals = jnp.asarray(np.ones((40, 4), np.float32))
        ts.update(ids[:40], vals)  # 64-wide bucket
        ts.update(ids[:16], vals[:16])  # 16-wide bucket
        ts.update(ids[:9], vals[:9])  # 16-wide bucket again
        assert ts.stats.compiles == 2
        ts.update(ids[:10], vals[:10])
        assert ts.stats.compiles == 2  # still inside the 16 bucket

    def test_reset_evict_admit_never_recompile_once_warm(self):
        ts = _tiny_set(64)
        ids = ts.tenant_ids()
        vals = jnp.asarray(np.ones((5, 4), np.float32))
        ts.update(ids[:5], vals)
        ts.reset(ids[:5])  # first width-8 reset program
        ts.evict(ids[0])  # first width-1 scrub program
        ts.admit(ids[0])
        warm = ts.stats.compiles
        for _ in range(3):
            ts.update(ids[:5], vals)
            ts.reset(ids[1:6])
            ts.evict(ids[2])
            ts.admit(ids[2])
        assert ts.stats.compiles == warm
        # the template dispatcher's stability counters pin the same invariant
        stats = ts._dispatcher.stats
        assert stats.builds == 1
        assert stats.repartitions == 0 and stats.migrations == 0
        assert stats.stable_hits > 0

    def test_compute_executable_is_cached(self):
        ts = _tiny_set(16)
        ids = ts.tenant_ids()
        vals = jnp.asarray(np.ones((3, 4), np.float32))
        ts.update(ids[:3], vals)
        before = ts.stats.compiles
        ts.compute(ids[:3])
        assert ts.stats.compiles == before + 1
        ts.compute(ids[1:4])
        ts.compute(ids[:4])  # k=4 -> same pow2 bucket as k=3
        assert ts.stats.compiles == before + 1
        assert ts.stats.cache_hits == 2


# -------------------------------------------------------------- immutability --
class TestMaskedImmutability:
    def test_absent_tenants_rows_are_bitwise_untouched(self):
        ts = _tiny_set(8)
        ids = ts.tenant_ids()
        initial = {
            ln: {k: np.asarray(v) for k, v in st.items()}
            for ln, st in ts.stacked_states.items()
        }
        vals = jnp.asarray(np.random.default_rng(0).normal(size=(2, 4)), jnp.float32)
        for _ in range(3):
            ts.update([ids[0], ids[2]], vals)
        untouched_rows = [1, 3, 4, 5, 6, 7]
        for ln, st in ts.stacked_states.items():
            for k, leaf in st.items():
                assert np.array_equal(
                    np.asarray(leaf)[untouched_rows], initial[ln][k][untouched_rows]
                ), (ln, k)

    def test_reset_of_some_leaves_others_mid_streak(self):
        ts = _tiny_set(8)
        ids = ts.tenant_ids()
        vals = jnp.asarray(np.ones((3, 4), np.float32))
        ts.update(ids[:3], vals)
        before = np.asarray(ts.stacked_states["mean"]["total"]).copy()
        ts.reset([ids[1]])
        after = np.asarray(ts.stacked_states["mean"]["total"])
        assert after[1] == 0.0
        assert np.array_equal(after[[0, 2]], before[[0, 2]])

    def test_evicted_slot_is_scrubbed_for_the_next_tenant(self):
        ts = _tiny_set(4, n_admit=1)
        ts.update(["t0"], jnp.asarray(np.ones((1, 4), np.float32)))
        ts.evict("t0")
        ts.admit("newcomer")
        out = ts.compute(["newcomer"])
        assert np.asarray(out["newcomer"]["mean"]) == 0.0  # defaults, not t0's streak


# ----------------------------------------------------------- export / import --
class TestExportImport:
    def test_round_trip_is_bitwise(self):
        ts = _tiny_set(8)
        ids = ts.tenant_ids()
        vals = jnp.asarray(np.random.default_rng(1).normal(size=(2, 4)), jnp.float32)
        ts.update(ids[:2], vals)
        snap = ts.export_tenant(ids[0])
        other = _tiny_set(4, n_admit=0)
        other.import_tenant("moved", snap)
        a = ts.compute([ids[0]])[ids[0]]
        b = other.compute(["moved"])["moved"]
        for name in a:
            assert np.array_equal(np.asarray(a[name]), np.asarray(b[name]))
        assert other.tenant_update_counts()["moved"] == 1

    def test_import_does_not_touch_other_rows(self):
        ts = _tiny_set(8)
        ids = ts.tenant_ids()
        vals = jnp.asarray(np.ones((2, 4), np.float32))
        ts.update(ids[:2], vals)
        before = np.asarray(ts.stacked_states["mean"]["total"]).copy()
        snap = ts.export_tenant(ids[0])
        ts.import_tenant(ids[3], snap)
        after = np.asarray(ts.stacked_states["mean"]["total"])
        assert np.array_equal(after[[0, 1, 2]], before[[0, 1, 2]])
        assert after[3] == before[0]


# ------------------------------------------------------------- mixed / eager --
class TestEagerGroups:
    def test_mixed_stacked_and_eager_parity(self):
        template = mt.MetricCollection({"mean": TinyMean(), "cat": mt.CatMetric()})
        ts = mt.TenantSet(template, capacity=4)
        ts.admit("a"); ts.admit("b")
        vals = jnp.asarray([[1.0, 2.0], [3.0, 4.0]], jnp.float32)
        ts.update(["a", "b"], vals)
        ts.update(["b"], vals[:1] * 10)
        out = ts.compute()
        assert np.asarray(out["a"]["mean"]) == pytest.approx(1.5)
        assert np.allclose(np.asarray(out["a"]["cat"]), [1.0, 2.0])
        assert np.allclose(np.asarray(out["b"]["cat"]), [3.0, 4.0, 10.0, 20.0])
        assert ts.stats.eager_tenant_updates == 3  # 2 tenants + 1 tenant

    def test_fully_eager_template_works(self):
        ts = mt.TenantSet(mt.CatMetric(), capacity=2)
        ts.admit(0)
        ts.update([0], jnp.asarray([[1.0, 2.0]], jnp.float32))
        assert np.allclose(np.asarray(ts.compute()[0]["CatMetric"]), [1.0, 2.0])
        assert ts.stats.compiles == 0  # nothing stacked, nothing traced


# -------------------------------------------------------------------- errors --
class TestErrors:
    def test_duplicate_tenant_in_one_dispatch(self):
        ts = _tiny_set(4)
        with pytest.raises(MetricsUserError, match="duplicate tenant"):
            ts.update(["t0", "t0"], jnp.zeros((2, 4), jnp.float32))

    def test_unadmitted_tenant(self):
        ts = _tiny_set(4, n_admit=1)
        with pytest.raises(MetricsUserError, match="not admitted"):
            ts.update(["ghost"], jnp.zeros((1, 4), jnp.float32))

    def test_admit_twice(self):
        ts = _tiny_set(4, n_admit=1)
        with pytest.raises(MetricsUserError, match="already admitted"):
            ts.admit("t0")

    def test_admit_beyond_capacity(self):
        ts = _tiny_set(2)
        with pytest.raises(MetricsUserError, match="at capacity"):
            ts.admit("overflow")

    def test_evict_unknown(self):
        ts = _tiny_set(2)
        with pytest.raises(MetricsUserError, match="not admitted"):
            ts.evict("ghost")

    def test_bad_tenant_id_type(self):
        ts = _tiny_set(4, n_admit=0)
        for bad in (True, 1.5, ("a",)):
            with pytest.raises(MetricsUserError, match="str or int"):
                ts.admit(bad)

    def test_bad_template_type(self):
        with pytest.raises(MetricsUserError, match="Metric or MetricCollection"):
            mt.TenantSet({"acc": mt.Accuracy()}, capacity=4)

    def test_bad_capacity(self):
        with pytest.raises(MetricsUserError, match="capacity"):
            mt.TenantSet(TinyMean(), capacity=0)

    def test_unhashable_static_arg(self):
        # a set is a pytree *leaf* (unlike dict/list) and is unhashable
        ts = _tiny_set(4)
        with pytest.raises(MetricsUserError, match="hashable"):
            ts.update(["t0"], jnp.zeros((1, 4), jnp.float32), {"unhashable", "set"})

    def test_empty_dispatch_is_a_noop(self):
        ts = _tiny_set(4)
        ts.update([], jnp.zeros((0, 4), jnp.float32))
        ts.reset([])
        assert ts.stats.dispatches == 0 and ts.stats.compiles == 0
